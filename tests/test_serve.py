"""Serving-daemon tests: tenant QoS scheduling (starvation, budgets,
weighted fairness), page-cache pinning, endpoint semantics over real
HTTP, hard-pressure shed ordering, graceful drain, and per-tenant
accounting exactness."""

import json
import os
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pyarrow as pa
import pytest

import parquet_tpu as pq
from parquet_tpu.io.cache import PAGES, cache_stats, clear_caches, \
    page_pin_scope
from parquet_tpu.obs.ledger import LEDGER
from parquet_tpu.obs.metrics import REGISTRY, metrics_snapshot, \
    reset_metrics
from parquet_tpu.serve import ServeConfig, Server, load_config
from parquet_tpu.serve.codecs import expr_from_wire, parse_agg_spec
from parquet_tpu.serve.config import parse_bytes
from parquet_tpu.utils.pool import (TenantSpec, read_admission,
                                    tenant_context)


@pytest.fixture(autouse=True)
def _isolate():
    clear_caches(reset_stats=True)
    adm = read_admission()
    adm.clear_tenants()
    adm._reset()
    yield
    clear_caches(reset_stats=True)
    adm.clear_tenants()
    adm._reset()


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    """Two read files + one writable table directory."""
    td = tmp_path_factory.mktemp("serve_corpus")
    paths = []
    for fi in range(2):
        n = 4000
        base = fi * 100_000
        p = str(td / f"f{fi}.parquet")
        pq.write_table(
            pa.table({"k": np.arange(base, base + n, dtype=np.int64),
                      "v": (np.arange(n, dtype=np.int64) * 3) % 1000,
                      "s": [f"s{i % 97}" for i in range(n)]}),
            p, options=pq.WriterOptions(row_group_size=800))
        paths.append(p)
    tdir = str(td / "tbl")
    seed = pa.table({"k": np.arange(10, dtype=np.int64),
                     "v": np.arange(10, dtype=np.int64)})
    w = pq.DatasetWriter(tdir, pq.schema_from_arrow(seed.schema),
                         sorting=[pq.SortingColumn("k")])
    w.write_arrow(seed)
    w.commit()
    w.close()
    return {"paths": paths, "table": tdir}


def _post(url, doc, tenant="default", timeout=60):
    req = urllib.request.Request(
        url, data=json.dumps(doc).encode(),
        headers={"X-Tenant": tenant, "Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, r.read()


def _get(url, timeout=30):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, r.read()


def _config(corpus, **tenants) -> dict:
    return {"datasets": {"events": {"paths": corpus["paths"]},
                         "tbl": {"table": corpus["table"],
                                 "writable": True, "sorting": "k"}},
            "tenants": tenants}


# ---------------------------------------------------------------------------
# config + codecs
# ---------------------------------------------------------------------------


def test_parse_bytes():
    assert parse_bytes(None) is None
    assert parse_bytes(123) == 123
    assert parse_bytes("64MiB") == 64 << 20
    assert parse_bytes("1kb") == 1000
    assert parse_bytes("2GiB") == 2 << 30
    with pytest.raises(ValueError):
        parse_bytes("lots")


def test_config_validation(corpus):
    cfg = ServeConfig.from_dict(_config(
        corpus, online={"class": "latency", "budget_bytes": "1MiB",
                        "weight": 2.0, "pin_bytes": 4096}))
    assert cfg.tenants["online"].klass == "latency"
    assert cfg.tenants["online"].budget_bytes == 1 << 20
    assert cfg.pin_bytes["online"] == 4096
    assert cfg.klass_for("online", "scan") == "latency"  # contract wins
    assert cfg.klass_for("anon", "scan") == "bulk"  # endpoint default
    assert cfg.klass_for("anon", "lookup") == "latency"
    with pytest.raises(ValueError):
        ServeConfig.from_dict({"datasets": {}})
    with pytest.raises(ValueError):
        ServeConfig.from_dict({"datasets": {"x": {"paths": ["p"],
                                                  "table": "t"}}})
    with pytest.raises(ValueError):
        ServeConfig.from_dict({"datasets": {"x": {"paths": ["p"]}},
                               "tenants": {"t": {"class": "vip"}}})
    with pytest.raises(ValueError):
        ServeConfig.from_dict({"datasets": {"x": {"paths": ["p"]}},
                               "nope": 1})


def test_load_config_file(corpus, tmp_path):
    p = tmp_path / "serve.json"
    p.write_text(json.dumps(_config(corpus)))
    cfg = load_config(str(p))
    assert set(cfg.datasets) == {"events", "tbl"}
    bad = tmp_path / "bad.json"
    bad.write_text("{nope")
    with pytest.raises(ValueError):
        load_config(str(bad))


def test_expr_from_wire_forms():
    assert expr_from_wire(None) is None
    e = expr_from_wire({"and": [{"col": "v", "ge": 1, "le": 5},
                                {"not": {"col": "s", "in": ["a"]}},
                                {"or": [{"col": "k", "eq": 7},
                                        {"col": "k", "null": False}]}]})
    assert isinstance(e, pq.Expr)
    with pytest.raises(ValueError):
        expr_from_wire({"col": "v", "eq": 1, "le": 5})
    with pytest.raises(ValueError):
        expr_from_wire({"ge": 1})
    with pytest.raises(ValueError):
        expr_from_wire({"col": "v", "gt": 1})
    with pytest.raises(ValueError):
        expr_from_wire({"and": []})


def test_parse_agg_spec():
    assert parse_agg_spec("count").name == "count(*)"
    assert parse_agg_spec("count:v").name == "count(v)"
    assert parse_agg_spec("avg:v").name == "avg(v)"
    assert parse_agg_spec("var:v").name == "variance(v)"
    assert parse_agg_spec("var:v:sample").name == "variance(v,sample)"
    assert parse_agg_spec("top:v:3").name == "top_k(v,3)"
    for bad in ("avg", "sum:", "top:v", "top:v:x", "median:v"):
        with pytest.raises(ValueError):
            parse_agg_spec(bad)


# ---------------------------------------------------------------------------
# scheduler: priority classes, tenant budgets, weighted fairness
# ---------------------------------------------------------------------------


def test_tenant_budget_isolated_lanes(monkeypatch):
    """A tenant blocked on its own budget never blocks another lane."""
    adm = read_admission()
    adm.configure_tenants({"b": TenantSpec("b", budget_bytes=100,
                                           klass="bulk"),
                           "l": TenantSpec("l", budget_bytes=100,
                                           klass="latency")})
    with tenant_context("b", "bulk"):
        g0 = adm.acquire(100, tier="scan")
    assert g0 == 100
    got = []

    def bulk_waiter():
        with tenant_context("b", "bulk"):
            g = adm.acquire(50, tier="scan")
            got.append(g)
            adm.release(g, tier="scan", tenant="b")

    t = threading.Thread(target=bulk_waiter)
    t.start()
    time.sleep(0.05)
    assert not got  # bulk lane saturated
    with tenant_context("l", "latency"):
        g1 = adm.acquire(80, tier="lookup")  # bypasses the bulk ticket
        assert g1 == 80
        adm.release(g1, tier="lookup", tenant="l")
    assert not got
    adm.release(g0, tier="scan", tenant="b")
    t.join(2)
    assert got == [50]
    assert adm.tenant_high_water["b"] <= 100
    assert adm.tenant_high_water["l"] <= 100


def test_untagged_fifo_preserved(monkeypatch):
    """Library traffic without a tenant keeps strict FIFO: a large early
    waiter is never starved by later small arrivals."""
    monkeypatch.setenv("PARQUET_TPU_LOOKUP_BUDGET", "100")
    adm = read_admission()
    g0 = adm.acquire(100)
    order = []

    def waiter(name, nbytes):
        g = adm.acquire(nbytes)
        order.append(name)
        time.sleep(0.05)
        adm.release(g)

    big = threading.Thread(target=waiter, args=("big", 90))
    big.start()
    time.sleep(0.05)
    small = threading.Thread(target=waiter, args=("small", 5))
    small.start()
    time.sleep(0.05)
    adm.release(g0)
    big.join(2)
    small.join(2)
    assert order == ["big", "small"]  # arrival order, not fit order


def test_latency_class_scheduled_before_bulk(monkeypatch):
    """Under shared-budget contention, a later-arriving latency ticket
    is granted before earlier bulk tickets."""
    monkeypatch.setenv("PARQUET_TPU_READ_BUDGET", "100")
    adm = read_admission()
    adm.configure_tenants({"b": TenantSpec("b", klass="bulk"),
                           "l": TenantSpec("l", klass="latency")})
    with tenant_context("b", "bulk"):
        g0 = adm.acquire(100, tier="scan")
    order = []

    def waiter(tenant, klass, tier):
        with tenant_context(tenant, klass):
            g = adm.acquire(60, tier=tier)
            order.append(tenant)
            time.sleep(0.02)
            adm.release(g, tier=tier, tenant=tenant)

    tb = threading.Thread(target=waiter, args=("b", "bulk", "scan"))
    tb.start()
    time.sleep(0.05)
    tl = threading.Thread(target=waiter, args=("l", "latency", "lookup"))
    tl.start()
    time.sleep(0.05)
    adm.release(g0, tier="scan", tenant="b")
    tb.join(2)
    tl.join(2)
    assert order == ["l", "b"]  # class rank beats arrival order


def test_weighted_fairness_vtime():
    """Within one class, the heavier-weight tenant's virtual time grows
    slower, so it sorts ahead under contention."""
    adm = read_admission()
    adm.configure_tenants(
        {"heavy": TenantSpec("heavy", weight=4.0, budget_bytes=1 << 20),
         "light": TenantSpec("light", weight=1.0, budget_bytes=1 << 20)})
    for _ in range(4):
        with tenant_context("heavy", "default"):
            g = adm.acquire(1000, tier="scan")
            adm.release(g, tier="scan", tenant="heavy")
        with tenant_context("light", "default"):
            g = adm.acquire(1000, tier="scan")
            adm.release(g, tier="scan", tenant="light")
    # grants are unbudgeted here (no caps) so everything admits; the
    # fairness clock still advances per spec
    assert adm._vtime["heavy"] < adm._vtime["light"]


def test_tenant_debug_shape():
    adm = read_admission()
    adm.configure_tenants([TenantSpec("a", budget_bytes=10, weight=2.0,
                                      klass="latency")])
    dbg = adm.tenant_debug()
    assert dbg["a"]["class"] == "latency"
    assert dbg["a"]["budget_bytes"] == 10
    assert dbg["a"]["in_flight_bytes"] == 0
    with pytest.raises(ValueError):
        adm.configure_tenants([TenantSpec("w", weight=0.0)])
    with pytest.raises(TypeError):
        adm.configure_tenants(["nope"])


# ---------------------------------------------------------------------------
# page-cache pinning
# ---------------------------------------------------------------------------


def test_pin_cap_eviction_refusal():
    arr = np.arange(128, dtype=np.int64)  # 1 KiB
    with page_pin_scope("tA", 3000):
        for i in range(5):  # cap admits 2 pages, refuses 3
            PAGES.put((("f", 1, 2), 0, "c", i), arr, None, 0, 128)
    st = cache_stats()
    assert st.page_pins == 2
    assert st.page_pin_refusals == 3
    assert PAGES.pinned_bytes("tA") == 2048
    # pinned entries survive a full shrink; LRU entries do not
    PAGES.shrink_to(0)
    assert PAGES.pinned_bytes("tA") == 2048
    assert PAGES.get((("f", 1, 2), 0, "c", 0)) is not None
    assert PAGES.get((("f", 1, 2), 0, "c", 4)) is None
    # ledger account tracks the pinned region exactly
    assert LEDGER.account("cache.page_pinned").resident == 2048
    # unpin demotes back into the LRU
    assert PAGES.unpin_tenant("tA") == 2
    assert PAGES.pinned_bytes() == 0
    assert LEDGER.account("cache.page_pinned").resident == 0
    assert PAGES.get((("f", 1, 2), 0, "c", 0)) is not None


def test_pin_scope_zero_cap_noop():
    with page_pin_scope("t", 0):
        PAGES.put((("f", 1, 2), 0, "c", 0), np.arange(4), None, 0, 4)
    assert PAGES.pinned_bytes() == 0


# ---------------------------------------------------------------------------
# endpoints over real HTTP
# ---------------------------------------------------------------------------


def test_endpoints_end_to_end(corpus):
    cfg = _config(corpus,
                  online={"class": "latency", "pin_bytes": "2MiB",
                          "budget_bytes": "16MiB"},
                  batch={"class": "bulk", "budget_bytes": "8MiB"})
    with Server(cfg, port=0) as srv:
        u = srv.url
        # lookup: values row-aligned, missing key empty, strings decode
        st, body = _post(u + "/v1/lookup",
                         {"dataset": "events", "column": "k",
                          "keys": [5, 100005, 42424242],
                          "columns": ["v", "s"]}, tenant="online")
        doc = json.loads(body)
        assert doc["rows_total"] == 2
        assert doc["hits"][0]["values"]["v"] == [15 % 1000]
        assert doc["hits"][1]["values"]["s"] == ["s5"]
        assert doc["hits"][2]["rows"] == []
        # pinned pages landed for the latency tenant
        assert PAGES.pinned_bytes("online") > 0
        # scan: streamed JSON lines with a done summary
        st, body = _post(u + "/v1/scan",
                         {"dataset": "events",
                          "where": {"col": "v", "le": 8},
                          "columns": ["k", "v"]}, tenant="batch")
        lines = [json.loads(x) for x in body.decode().splitlines()]
        assert lines[-1]["done"]
        naive = sum(int(((np.arange(4000) * 3) % 1000 <= 8).sum())
                    for _ in range(2))
        assert lines[-1]["num_rows"] == naive
        # scan: arrow IPC stream parses and matches
        st, body = _post(u + "/v1/scan",
                         {"dataset": "events", "format": "arrow",
                          "where": {"col": "v", "le": 8}}, tenant="batch")
        import io

        tab = pa.ipc.open_stream(io.BytesIO(body)).read_all()
        assert tab.num_rows == naive
        # aggregate incl. derived folds
        st, body = _post(u + "/v1/aggregate",
                         {"dataset": "events",
                          "aggs": ["count", "avg:v", "var:v"]},
                         tenant="online")
        doc = json.loads(body)["aggregates"]
        v = np.concatenate([(np.arange(4000) * 3) % 1000] * 2)
        assert doc["count(*)"] == 8000
        assert abs(doc["avg(v)"] - v.mean()) < 1e-9
        assert abs(doc["variance(v)"] - v.var()) < 1e-6
        # group-by over the wire
        st, body = _post(u + "/v1/aggregate",
                         {"dataset": "events", "aggs": ["count"],
                          "group_by": "s",
                          "where": {"col": "s", "in": ["s0", "s1"]}},
                         tenant="online")
        doc = json.loads(body)
        assert doc["groups"] == ["s0", "s1"]
        # write: commit + snapshot refresh
        st, body = _post(u + "/v1/write",
                         {"dataset": "tbl",
                          "rows": {"k": [500, 501], "v": [1, 2]}},
                         tenant="batch")
        assert json.loads(body)["rows"] == 2
        st, body = _post(u + "/v1/lookup",
                         {"dataset": "tbl", "column": "k",
                          "keys": [500], "columns": ["v"]},
                         tenant="online")
        assert json.loads(body)["hits"][0]["values"]["v"] == [1]


def test_endpoint_errors(corpus):
    with Server(_config(corpus), port=0) as srv:
        u = srv.url
        for doc, path, code in [
                ({"dataset": "nope", "column": "k", "keys": [1]},
                 "/v1/lookup", 404),
                ({"dataset": "events", "column": "k", "keys": []},
                 "/v1/lookup", 400),
                ({"dataset": "events", "column": "k"}, "/v1/lookup", 400),
                ({"dataset": "events", "aggs": ["median:v"]},
                 "/v1/aggregate", 400),
                ({"dataset": "events", "format": "csv"}, "/v1/scan", 400),
                ({"dataset": "events", "rows": {"k": [1]}},
                 "/v1/write", 403),
                ({"dataset": "tbl", "rows": {"k": [1], "v": [1, 2]}},
                 "/v1/write", 400),
                ({}, "/v1/nope", 404)]:
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post(srv.url + path, doc)
            assert ei.value.code == code, (path, doc)
        # malformed JSON body
        req = urllib.request.Request(u + "/v1/lookup", data=b"{nope")
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=10)
        assert ei.value.code == 400
        # GET scrape surface answers on the same port
        assert _get(u + "/healthz")[1] == b"ok\n"
        assert b"parquet_tpu_serve_requests_total" in _get(u + "/metrics")[1]
        dz = json.loads(_get(u + "/debugz")[1])
        assert "tenants" in dz and "admission" in dz


def test_per_tenant_metric_families(corpus):
    reset_metrics()
    cfg = _config(corpus, online={"class": "latency"},
                  batch={"class": "bulk"})
    with Server(cfg, port=0) as srv:
        _post(srv.url + "/v1/lookup", {"dataset": "events", "column": "k",
                                       "keys": [1]}, tenant="online")
        _post(srv.url + "/v1/scan", {"dataset": "events",
                                     "where": {"col": "v", "le": 0}},
              tenant="batch")
        prom = _get(srv.url + "/metrics")[1].decode()
    assert ('parquet_tpu_serve_requests_total{class="latency",'
            'tenant="online"} 1') in prom
    assert ('parquet_tpu_serve_requests_total{class="bulk",'
            'tenant="batch"} 1') in prom
    # pre-declared class families render even for untouched classes
    assert 'parquet_tpu_serve_shed_total{class="default"} 0' in prom
    assert "parquet_tpu_serve_request_s_bucket" in prom


def test_per_tenant_accounting_exactness(corpus):
    """OpReport sums == metrics_delta per window: every byte read inside
    requests attributes to exactly one tenant (no smearing)."""
    cfg = _config(corpus, a={"class": "latency"}, b={"class": "bulk"})
    with Server(cfg, port=0) as srv:
        u = srv.url
        clear_caches()
        before = metrics_snapshot()
        for i in range(3):
            _post(u + "/v1/lookup", {"dataset": "events", "column": "k",
                                     "keys": [i * 7, i * 7 + 1],
                                     "columns": ["v"]}, tenant="a")
        _post(u + "/v1/scan", {"dataset": "events",
                               "where": {"col": "v", "le": 50}},
              tenant="b")
        after = metrics_snapshot()
        stats = srv.tenant_stats.snapshot()
    delta = (after["counters"].get("read.bytes_read", 0)
             - before["counters"].get("read.bytes_read", 0))
    folded = sum(r["bytes_read"] for r in stats.values())
    assert folded == delta, (folded, delta, stats)
    assert stats["a"]["requests"] == 3
    assert stats["b"]["requests"] == 1
    assert stats["a"]["bytes_read"] > 0


# ---------------------------------------------------------------------------
# starvation proof (the acceptance criterion)
# ---------------------------------------------------------------------------


def test_starvation_matrix(corpus):
    """With a bulk tenant saturating its scan budget, the latency
    tenant's 64-key lookup p99 (serve.request_s{class=latency}) stays
    within 2x of its solo p99, and both tenants' gate high-water stays
    <= their configured budgets."""
    lat_hist = REGISTRY.histogram("serve.request_s",
                                  labels={"class": "latency"})
    cfg = _config(corpus,
                  lat={"class": "latency", "budget_bytes": 8 << 20},
                  bulk={"class": "bulk", "budget_bytes": 256 << 10})
    with Server(cfg, port=0) as srv:
        u = srv.url

        def lookup(i):
            keys = [int(k) for k in range(i * 64, i * 64 + 64)]
            _post(u + "/v1/lookup", {"dataset": "events", "column": "k",
                                     "keys": keys, "columns": ["v"]},
                  tenant="lat")

        lookup(0)  # warm the footer path
        reset_metrics()
        for i in range(12):
            lookup(i % 8)
        solo_p99 = lat_hist.percentile(0.99)
        assert solo_p99 is not None
        # bulk hammer: unselective scans, clamped by the tiny budget
        stop = threading.Event()

        def bulk_hammer():
            while not stop.is_set():
                try:
                    _post(u + "/v1/scan",
                          {"dataset": "events",
                           "where": {"col": "v", "ge": 0}},
                          tenant="bulk")
                except (urllib.error.URLError, OSError):
                    return

        threads = [threading.Thread(target=bulk_hammer)
                   for _ in range(3)]
        reset_metrics()
        for t in threads:
            t.start()
        try:
            time.sleep(0.1)
            for i in range(12):
                lookup(i % 8)
            mixed_p99 = lat_hist.percentile(0.99)
        finally:
            stop.set()
            for t in threads:
                t.join(30)
        adm = read_admission()
        hw = dict(adm.tenant_high_water)
        assert mixed_p99 is not None
        # 2x the solo p99, floored against micro-jitter on tiny absolute
        # latencies (the contract is "not starved", not "zero cost")
        assert mixed_p99 <= max(2.0 * solo_p99, 0.25), \
            (solo_p99, mixed_p99)
        assert hw.get("bulk", 0) <= 256 << 10, hw
        assert hw.get("lat", 0) <= 8 << 20, hw


# ---------------------------------------------------------------------------
# hard-pressure shed ordering + drain
# ---------------------------------------------------------------------------


def test_hard_pressure_sheds_bulk_first(corpus, monkeypatch):
    cfg = _config(corpus, lat={"class": "latency", "pin_bytes": "4MiB"},
                  bulk={"class": "bulk"})
    with Server(cfg, port=0) as srv:
        u = srv.url
        # warm the latency tenant's lookup fully (pages pinned) BEFORE
        # pressure: a pinned-warm lookup takes no admission grant
        for _ in range(2):
            _post(u + "/v1/lookup", {"dataset": "events", "column": "k",
                                     "keys": [1, 2, 3],
                                     "columns": ["v"]}, tenant="lat")
        ballast = LEDGER.account("test.serve_ballast")
        try:
            ballast.set(1 << 30)
            monkeypatch.setenv("PARQUET_TPU_MEM_HARD", str(1 << 20))
            assert _get(u + "/healthz")[1] == b"hard\n"
            # bulk scan sheds promptly with 429 + Retry-After
            t0 = time.perf_counter()
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post(u + "/v1/scan", {"dataset": "events"},
                      tenant="bulk")
            assert ei.value.code == 429
            assert ei.value.headers.get("Retry-After") is not None
            assert time.perf_counter() - t0 < 5.0
            # the latency tenant's warm lookup still serves under hard
            st, body = _post(u + "/v1/lookup",
                             {"dataset": "events", "column": "k",
                              "keys": [1, 2, 3], "columns": ["v"]},
                             tenant="lat")
            assert json.loads(body)["rows_total"] == 3
            # shed accounting: per-class counter + per-tenant debugz
            snap = metrics_snapshot()["counters"]
            assert snap['serve.shed{class=bulk}'] >= 1
            assert snap['serve.shed{class=bulk,tenant=bulk}'] >= 1
            dz = json.loads(_get(u + "/debugz")[1])
            assert dz["tenants"]["bulk"]["shed"] >= 1
        finally:
            ballast.set(0)
            monkeypatch.delenv("PARQUET_TPU_MEM_HARD")
        assert _get(u + "/healthz")[1] == b"ok\n"


def test_graceful_drain(corpus):
    with Server(_config(corpus), port=0) as srv:
        u = srv.url
        results = []

        def inflight():
            st, body = _post(u + "/v1/aggregate",
                             {"dataset": "events",
                              "aggs": ["count", "distinct:v"]})
            results.append(json.loads(body))

        t = threading.Thread(target=inflight)
        t.start()
        time.sleep(0.02)
        assert srv.close(drain=True) is True
        t.join(10)
        assert results and results[0]["aggregates"]["count(*)"] == 8000
    # close released tenant state
    assert read_admission().tenant_debug() == {}


def test_close_clears_pins_and_tenants(corpus):
    cfg = _config(corpus, lat={"class": "latency", "pin_bytes": "4MiB",
                               "budget_bytes": "1MiB"})
    srv = Server(cfg, port=0)
    _post(srv.url + "/v1/lookup", {"dataset": "events", "column": "k",
                                   "keys": [9]}, tenant="lat")
    assert PAGES.pinned_bytes("lat") > 0
    srv.close()
    assert PAGES.pinned_bytes("lat") == 0
    assert read_admission().tenant_spec("lat") is None
    # idempotent
    assert srv.close() is True


# ---------------------------------------------------------------------------
# review regressions
# ---------------------------------------------------------------------------


def test_intra_lane_fifo_no_leapfrog():
    """A ticket blocked on its own tenant budget blocks its whole LANE:
    later small same-tenant tickets cannot leapfrog an earlier big one
    (the intra-lane anti-starvation guarantee)."""
    adm = read_admission()
    adm.configure_tenants({"t": TenantSpec("t", budget_bytes=100,
                                           klass="bulk")})
    with tenant_context("t", "bulk"):
        g0 = adm.acquire(60, tier="scan")
    order = []

    def waiter(name, nbytes):
        with tenant_context("t", "bulk"):
            g = adm.acquire(nbytes, tier="scan")
            order.append(name)
            time.sleep(0.05)
            adm.release(g, tier="scan", tenant="t")

    big = threading.Thread(target=waiter, args=("big", 80))
    big.start()
    time.sleep(0.05)
    # 30 bytes WOULD fit (60+30 <= 100) — but the big lane-mate is ahead
    small = threading.Thread(target=waiter, args=("small", 30))
    small.start()
    time.sleep(0.1)
    assert order == []  # neither granted while the lane head waits
    adm.release(g0, tier="scan", tenant="t")
    big.join(2)
    small.join(2)
    assert order == ["big", "small"]


def test_vtime_floor_no_idle_priority_banking():
    """A newly-configured (or long-idle) tenant joins the fairness clock
    at NOW — its tickets do not outrank a veteran's on lifetime bytes."""
    adm = read_admission()
    adm.configure_tenants(
        {"vet": TenantSpec("vet", weight=1.0, budget_bytes=1 << 20),
         "new": TenantSpec("new", weight=1.0, budget_bytes=1 << 20)})
    for _ in range(5):  # the veteran drains lots of bytes first
        with tenant_context("vet", "default"):
            g = adm.acquire(100_000, tier="scan")
            adm.release(g, tier="scan", tenant="vet")
    with tenant_context("new", "default"):
        g = adm.acquire(1000, tier="scan")
        adm.release(g, tier="scan", tenant="new")
    # the newcomer's clock started at the floor, not at zero
    assert adm._vtime["new"] >= adm._vtime["vet"] - 100_000


def test_arrow_stream_empty_byte_array_schema(corpus, tmp_path):
    """A file matching zero rows of a BYTE_ARRAY column still emits a
    binary-typed (not null-typed) batch, so a multi-file Arrow stream
    keeps one schema."""
    from parquet_tpu.serve.codecs import columns_to_arrow_batch

    empty = columns_to_arrow_batch({"s": [], "k": np.array([], np.int64)})
    full = columns_to_arrow_batch({"s": [b"x", None],
                                   "k": np.array([1, 2], np.int64)})
    assert empty.schema.equals(full.schema), (empty.schema, full.schema)
    # end to end: a where-tree matching rows in only ONE of two files
    with Server(_config(corpus), port=0) as srv:
        body = _post(srv.url + "/v1/scan",
                     {"dataset": "events", "format": "arrow",
                      "columns": ["k", "s"],
                      "where": {"col": "k", "ge": 100_000}})[1]
        import io

        tab = pa.ipc.open_stream(io.BytesIO(body)).read_all()
        assert tab.num_rows == 4000  # file 2 only; file 1 contributes 0


def test_config_rejects_unknown_qos_keys(corpus):
    with pytest.raises(ValueError, match="unknown keys"):
        ServeConfig.from_dict(
            {"datasets": {"x": {"paths": ["p"]}},
             "tenants": {"t": {"budget": "64MiB"}}})  # typo'd key
    with pytest.raises(ValueError, match="unknown keys"):
        ServeConfig.from_dict(
            {"datasets": {"x": {"paths": ["p"], "sort": "k"}}})


def test_unknown_tenant_collapses_to_default(corpus):
    """Arbitrary X-Tenant values must not mint unbounded per-value
    metric series / gate lanes / stats rows — unknown tenants ride the
    default identity."""
    cfg = _config(corpus, online={"class": "latency"})
    with Server(cfg, port=0) as srv:
        for i in range(5):
            _post(srv.url + "/v1/lookup",
                  {"dataset": "events", "column": "k", "keys": [i]},
                  tenant=f"scanner-{i}")
        stats = srv.tenant_stats.snapshot()
        assert set(stats) == {"default"}, set(stats)
        assert stats["default"]["requests"] == 5
        prom = _get(srv.url + "/metrics")[1].decode()
        assert 'tenant="scanner-0"' not in prom
        assert 'tenant="default"' in prom


def test_error_responses_close_connection(corpus):
    """A 4xx that may leave the request body unread must not keep the
    connection alive (the next request would parse the leftover body)."""
    import http.client

    with Server(_config(corpus), port=0) as srv:
        conn = http.client.HTTPConnection(srv.host, srv.port, timeout=10)
        conn.request("POST", "/v1/lookup", body=b"{nope",
                     headers={"Content-Length": "5"})
        resp = conn.getresponse()
        assert resp.status == 400
        assert resp.getheader("Connection") == "close"
        conn.close()


def test_second_server_refused(corpus):
    with Server(_config(corpus), port=0):
        with pytest.raises(RuntimeError, match="already running"):
            Server(_config(corpus), port=0)
    # after close, a new one boots (and a failed bind leaves no residue)
    with pytest.raises(OSError):
        Server(_config(corpus), host="999.invalid.host.name", port=0)
    with Server(_config(corpus), port=0):
        pass


def test_arrow_scan_zero_row_dataset(tmp_path):
    """format=arrow with no 'where' over files yielding zero batches
    still produces a valid (empty) IPC stream carrying the schema."""
    import io

    p = str(tmp_path / "empty.parquet")
    pq.write_table(pa.table({"k": pa.array([], pa.int64()),
                             "s": pa.array([], pa.string())}), p)
    with Server({"datasets": {"e": {"paths": [p]}}}, port=0) as srv:
        body = _post(srv.url + "/v1/scan",
                     {"dataset": "e", "format": "arrow"})[1]
        tab = pa.ipc.open_stream(io.BytesIO(body)).read_all()
        assert tab.num_rows == 0
        assert set(tab.schema.names) == {"k", "s"}


def test_untagged_traffic_joins_fairness_floor():
    """Library (untagged) tickets enqueue at the fairness floor, not at
    vtime 0 — sustained untagged traffic cannot permanently outrank a
    default-class tenant that has accrued vtime."""
    adm = read_admission()
    adm.configure_tenants(
        {"t": TenantSpec("t", weight=1.0, budget_bytes=1 << 20)})
    with tenant_context("t", "default"):
        g = adm.acquire(500_000, tier="scan")  # advances the floor later
        adm.release(g, tier="scan", tenant="t")
    with tenant_context("t", "default"):
        g = adm.acquire(500_000, tier="scan")
        adm.release(g, tier="scan", tenant="t")
    # the tenant's vtime is ~1e6; the floor advanced with its grants —
    # an untagged ticket enqueued now keys at the floor, not 0.0
    assert adm._vfloor > 0


# ---------------------------------------------------------------------------
# bearer tokens (ISSUE 16 satellite)
# ---------------------------------------------------------------------------


def _post_h(url, doc, tenant="default", headers=None, timeout=60):
    hdrs = {"X-Tenant": tenant, "Content-Type": "application/json"}
    hdrs.update(headers or {})
    req = urllib.request.Request(url, data=json.dumps(doc).encode(),
                                 headers=hdrs)
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, r.read(), dict(r.headers)


LOOK = {"dataset": "events", "column": "k", "keys": [5]}


def test_bearer_token_auth(corpus):
    cfg = _config(corpus,
                  secure={"class": "latency", "token": "s3cret"},
                  open_={"class": "bulk"})
    with Server(cfg, port=0) as srv:
        u = srv.url + "/v1/lookup"
        # no credential → 401 with a challenge, nothing leaks
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post_h(u, LOOK, tenant="secure")
        assert ei.value.code == 401
        assert "Bearer" in ei.value.headers.get("WWW-Authenticate", "")
        # wrong credential → 401
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post_h(u, LOOK, tenant="secure",
                    headers={"Authorization": "Bearer nope"})
        assert ei.value.code == 401
        # right credential → 200
        st, body, _ = _post_h(u, LOOK, tenant="secure",
                              headers={"Authorization": "Bearer s3cret"})
        assert st == 200 and json.loads(body)["rows_total"] == 1
        # tokenless tenants are unaffected
        assert _post_h(u, LOOK, tenant="open_")[0] == 200
        # the failure counter is live
        assert REGISTRY.counter("serve.auth_failures").value >= 2


def test_token_rotation_under_chaos(corpus):
    """Rotation races in-flight requests: every response is a clean 200
    or 401 (never a 5xx, never a hang), old token dies, new token
    works — even while a chaos hook partitions fleet peers (rotation
    must not depend on fleet health)."""
    from parquet_tpu.io.faults import PeerChaos, set_peer_chaos

    cfg = _config(corpus, secure={"token": "old"})
    with Server(cfg, port=0) as srv:
        u = srv.url + "/v1/lookup"
        chaos = PeerChaos()
        set_peer_chaos(chaos)
        chaos.partition("nobody")  # armed hook, daemon has no fleet
        try:
            codes = []
            stop = threading.Event()

            def hammer(tok):
                while not stop.is_set():
                    try:
                        st, _, _ = _post_h(
                            u, LOOK, tenant="secure",
                            headers={"Authorization": f"Bearer {tok}"})
                        codes.append(st)
                    except urllib.error.HTTPError as e:
                        codes.append(e.code)

            threads = [threading.Thread(target=hammer, args=(t,))
                       for t in ("old", "new")]
            for t in threads:
                t.start()
            time.sleep(0.1)
            srv.rotate_token("secure", "new")
            time.sleep(0.1)
            stop.set()
            for t in threads:
                t.join(30)
            assert set(codes) <= {200, 401} and 200 in codes
            # post-rotation: old dead, new live
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post_h(u, LOOK, tenant="secure",
                        headers={"Authorization": "Bearer old"})
            assert ei.value.code == 401
            assert _post_h(u, LOOK, tenant="secure",
                           headers={"Authorization": "Bearer new"})[0] \
                == 200
        finally:
            set_peer_chaos(None)


# ---------------------------------------------------------------------------
# per-tenant QPS (ISSUE 16 satellite)
# ---------------------------------------------------------------------------


def test_qps_limit_429_retry_after(corpus):
    cfg = _config(corpus, limited={"qps": 0.5, "burst": 1},
                  free={"class": "latency"})
    with Server(cfg, port=0) as srv:
        u = srv.url + "/v1/lookup"
        before = REGISTRY.counter("serve.qps_rejections").value
        assert _post_h(u, LOOK, tenant="limited")[0] == 200
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post_h(u, LOOK, tenant="limited")
        assert ei.value.code == 429
        assert int(ei.value.headers["Retry-After"]) >= 1
        doc = json.loads(ei.value.read())
        assert doc["retry_after_s"] > 0
        assert REGISTRY.counter("serve.qps_rejections").value > before
        # other tenants are not collateral
        for _ in range(3):
            assert _post_h(u, LOOK, tenant="free")[0] == 200
        # the metric is pre-declared per tenant label too
        prom = _get(srv.url + "/metrics")[1].decode()
        assert "parquet_tpu_serve_qps_rejections_total" in prom


# ---------------------------------------------------------------------------
# scan pagination (ISSUE 16 satellite)
# ---------------------------------------------------------------------------


def test_scan_pagination_concatenates_byte_identically(corpus):
    scan = {"dataset": "events", "where": {"col": "v", "le": 500},
            "columns": ["k", "v"]}
    with Server(_config(corpus), port=0) as srv:
        u = srv.url + "/v1/scan"
        _, unbounded, _ = _post_h(u, scan)
        pages, token = [], None
        for _ in range(50):
            doc = dict(scan, limit=700)
            if token:
                doc["page_token"] = token
            _, body, hdrs = _post_h(u, doc)
            pages.append(body)
            token = hdrs.get("X-Next-Page-Token")
            if not token:
                break
        assert len(pages) > 1  # it actually paginated
        assert b"".join(pages) == unbounded
        # last page carries the cumulative done line
        last = json.loads(pages[-1].splitlines()[-1])
        unb = json.loads(unbounded.splitlines()[-1])
        assert last == unb and last["done"]
        # malformed inputs are clean 400s
        for doc in [dict(scan, limit=0), dict(scan, limit="x"),
                    dict(scan, page_token="@@@"),
                    dict(scan, limit=10, format="arrow")]:
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post_h(u, doc)
            assert ei.value.code == 400


# ---------------------------------------------------------------------------
# gzip response encoding (ISSUE 16 satellite)
# ---------------------------------------------------------------------------


def test_gzip_scan_and_aggregate_identity(corpus):
    import gzip as _gz

    scan = {"dataset": "events", "where": {"col": "v", "le": 200}}
    agg = {"dataset": "events", "aggs": ["count", "sum:v"]}
    with Server(_config(corpus), port=0) as srv:
        plain_scan = _post_h(srv.url + "/v1/scan", scan)[1]
        st, gz_scan, hdrs = _post_h(srv.url + "/v1/scan", scan,
                                    headers={"Accept-Encoding": "gzip"})
        assert hdrs.get("Content-Encoding") == "gzip"
        assert _gz.decompress(gz_scan) == plain_scan  # identity
        plain_agg = _post_h(srv.url + "/v1/aggregate", agg)[1]
        st, gz_agg, hdrs = _post_h(srv.url + "/v1/aggregate", agg,
                                   headers={"Accept-Encoding": "gzip"})
        assert hdrs.get("Content-Encoding") == "gzip"
        assert _gz.decompress(gz_agg) == plain_agg
        # lookups/writes stay plain regardless
        _, _, hdrs = _post_h(srv.url + "/v1/lookup", LOOK,
                             headers={"Accept-Encoding": "gzip"})
        assert "Content-Encoding" not in hdrs


def test_truncated_gzip_is_retryable():
    import gzip as _gz

    from parquet_tpu.errors import RemoteTransientError
    from parquet_tpu.io.remote import gunzip_body

    whole = _gz.compress(b"x" * 4096)
    assert gunzip_body(whole, host="h", path="/p") == b"x" * 4096
    with pytest.raises(RemoteTransientError):
        gunzip_body(whole[:-6], host="h", path="/p")  # torn member


# ---------------------------------------------------------------------------
# fleet config validation (ISSUE 16)
# ---------------------------------------------------------------------------


def test_cluster_config_validation(corpus):
    good = _config(corpus)
    good["cluster"] = {"self": "a", "peers": {"a": None,
                                              "b": "http://h:1"}}
    cfg = ServeConfig.from_dict(good)
    assert cfg.cluster.self_name == "a"
    assert cfg.cluster.peers["b"] == "http://h:1"
    for cluster in [{"peers": {"a": None}},           # self missing
                    {"self": "x", "peers": {"a": None}},  # not a member
                    {"self": "a", "peers": {}},       # empty
                    {"self": "a", "peers": {"a": None}, "ring": 3}]:
        bad = _config(corpus)
        bad["cluster"] = cluster
        with pytest.raises(ValueError):
            ServeConfig.from_dict(bad)
    # token/qps tenant knobs parse and validate
    cfg = ServeConfig.from_dict(_config(
        corpus, t={"token": "x", "qps": 2, "burst": 4}))
    assert cfg.tokens["t"] == "x"
    assert cfg.tenants["t"].qps == 2.0
    with pytest.raises(ValueError):
        ServeConfig.from_dict(_config(corpus, t={"token": 42}))
