"""Bounded-memory streaming reads (io/stream.py): batch correctness vs
pyarrow across types/batch sizes, plus actual IO-boundedness — the reference
streams O(page), not O(chunk) (SURVEY.md §5, PageBufferSize)."""

import io

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from parquet_tpu import ParquetFile, iter_batches


def _write(t: pa.Table, **kw) -> bytes:
    buf = io.BytesIO()
    pq.write_table(t, buf, **kw)
    return buf.getvalue()


def _concat_batches(pf, **kw):
    tables = [b.to_arrow() for b in iter_batches(pf, **kw)]
    assert tables
    return pa.concat_tables(tables)


def _mixed_table(n, rng):
    return pa.table({
        "i": pa.array(rng.integers(-(2**50), 2**50, n)),
        "oi": pa.array([None if i % 7 == 0 else i * 3 for i in range(n)],
                       type=pa.int64()),
        "f": pa.array(rng.random(n, dtype=np.float32)),
        "s": pa.array([f"s{i % 113}" for i in range(n)]),
        "lst": pa.array([None if i % 11 == 0 else
                         [int(x) for x in range(i % 5)] for i in range(n)],
                        type=pa.list_(pa.int64())),
    })


@pytest.mark.parametrize("batch_rows", [1, 7, 1000, 4096, 100000])
def test_stream_batches_equal_full_read(batch_rows, rng):
    n = 10000
    t = _mixed_table(n, rng)
    raw = _write(t, row_group_size=3000, data_page_size=2048)
    got = _concat_batches(ParquetFile(raw), batch_rows=batch_rows)
    want = pq.read_table(io.BytesIO(raw))
    assert got.num_rows == n
    for name in t.column_names:
        assert got.column(name).combine_chunks().equals(
            want.column(name).combine_chunks()), name


def test_stream_batch_sizes_and_column_subset(rng):
    n = 5000
    t = _mixed_table(n, rng)
    raw = _write(t, row_group_size=1700, data_page_size=4096)
    pf = ParquetFile(raw)
    sizes = []
    for b in iter_batches(pf, columns=["i", "oi"], batch_rows=999):
        sizes.append(b.num_rows)
        assert np.asarray(b["i"].values).ndim == 1
    assert sum(sizes) == n
    # batches are "at most batch_rows", snapped to row-group boundaries
    # when at least half-full (pyarrow's iter_batches behaves the same);
    # rg=1700 under batch_rows=999 → alternating 999 / 701 per row group
    assert all(s <= 999 for s in sizes)
    assert all(s == 999 or s * 2 >= 999 for s in sizes[:-1])


def test_stream_struct_columns(rng):
    rows = [None if i % 9 == 0 else {"a": i, "b": None if i % 4 == 0 else f"v{i}"}
            for i in range(3000)]
    t = pa.table({"st": pa.array(
        rows, type=pa.struct([("a", pa.int64()), ("b", pa.string())]))})
    raw = _write(t, row_group_size=1000, data_page_size=1024,
                 use_dictionary=False)
    got = _concat_batches(ParquetFile(raw), batch_rows=450)
    assert got.column("st").to_pylist() == t.column("st").to_pylist()


def test_stream_is_io_bounded(rng):
    """The streaming path must never pread a whole chunk: with many pages per
    chunk, the largest single read stays page-sized and the bytes touched by
    the first batch are a small fraction of the file."""
    n = 200_000
    t = pa.table({"x": pa.array(rng.integers(0, 1 << 40, n)),
                  "y": pa.array(rng.random(n))})
    raw = _write(t, row_group_size=n, data_page_size=8192,
                 use_dictionary=False, compression="none")
    pf = ParquetFile(raw)

    reads = []
    orig = pf.source.pread
    orig_view = pf.source.pread_view

    def spy(offset, size):
        reads.append(size)
        return orig(offset, size)

    def spy_view(offset, size):
        reads.append(size)
        return orig_view(offset, size)

    pf.source.pread = spy
    pf.source.pread_view = spy_view
    it = iter_batches(pf, batch_rows=4096)
    first = next(it)
    assert first.num_rows == 4096
    chunk_size = pf.row_group(0).column("x").meta.total_compressed_size
    assert max(reads) < chunk_size / 10, (max(reads), chunk_size)
    assert sum(reads) < len(raw) / 10, (sum(reads), len(raw))
    # draining the iterator still reads everything correctly
    total = first.num_rows + sum(b.num_rows for b in it)
    assert total == n


def test_stream_dictionary_decoded_once(rng):
    n = 30000
    t = pa.table({"s": pa.array([f"cat{i % 40}" for i in range(n)])})
    raw = _write(t, data_page_size=2048)
    pf = ParquetFile(raw)
    from parquet_tpu.utils.debug import counters

    before = counters.get("dict_pages_decoded")
    got = _concat_batches(pf, batch_rows=1234)
    assert got.column("s").combine_chunks().equals(
        pq.read_table(io.BytesIO(raw)).column("s").combine_chunks())
    # one dictionary decode per chunk, not one per page/batch
    assert counters.get("dict_pages_decoded") - before <= len(pf.row_groups)


def test_stream_empty_and_single_row(rng):
    t = pa.table({"x": pa.array(np.arange(1, dtype=np.int64))})
    raw = _write(t)
    batches = list(iter_batches(ParquetFile(raw), batch_rows=10))
    assert len(batches) == 1 and batches[0].num_rows == 1
    with pytest.raises(ValueError):
        list(iter_batches(ParquetFile(raw), batch_rows=0))


def test_stream_tolerates_unknown_page_types(rng, monkeypatch):
    """A non-data page inside the chunk stream (e.g. an index page) must not
    crash the batched page pull (review r4: AttributeError on the row
    estimate) — decode_chunk_host already skips such pages."""
    from parquet_tpu.format.enums import PageType as PT
    from parquet_tpu.io import reader as rd
    from parquet_tpu.io import stream as sm

    n = 4000
    t = pa.table({"x": pa.array(np.arange(n, dtype=np.int64))})
    raw = _write(t, row_group_size=1 << 30, data_page_size=2048)
    pf = ParquetFile(raw)

    from parquet_tpu.format import metadata as md

    idx_type = int(getattr(PT, "INDEX_PAGE", 4))
    fake = rd.PageInfo(
        header=md.PageHeader(type=idx_type, uncompressed_page_size=0,
                             compressed_page_size=0),
        payload=b"", offset=0)
    assert fake.page_type not in (PT.DATA_PAGE, PT.DATA_PAGE_V2,
                                  PT.DICTIONARY_PAGE)

    orig = rd.ColumnChunkReader.pages_streamed

    def with_fake(self, *a, **kw):
        yield fake  # unknown page type first
        yield from orig(self, *a, **kw)

    monkeypatch.setattr(rd.ColumnChunkReader, "pages_streamed", with_fake)
    got = [b for b in sm.iter_batches(pf, batch_rows=1500)]
    assert sum(b.num_rows for b in got) == n


def test_iter_batches_strict_batch_rows():
    """strict_batch_rows=True restores fixed batch sizes (except the last)
    even across row-group boundaries."""
    n, rg = 10_000, 1500
    t = pa.table({"x": pa.array(np.arange(n, dtype=np.int64))})
    buf = io.BytesIO()
    pq.write_table(t, buf, row_group_size=rg)
    pf = ParquetFile(buf.getvalue())
    sizes = [b.num_rows for b in pf.iter_batches(batch_rows=1000,
                                                 strict_batch_rows=True)]
    assert sizes == [1000] * 10
    got = np.concatenate([np.asarray(b["x"].values) for b in
                          pf.iter_batches(batch_rows=1000,
                                          strict_batch_rows=True)])
    np.testing.assert_array_equal(got, np.arange(n))


def test_pages_streamed_corrupt_inputs_raise_cleanly():
    """Bit-flipped / truncated chunks through the windowed native header
    scanner must raise CorruptedError (or decode to an error), never crash
    or loop; valid streams decode identically before and after."""
    rng = np.random.default_rng(33)
    n = 50000
    t = pa.table({"x": pa.array(rng.integers(0, 1 << 40, n))})
    buf = io.BytesIO()
    pq.write_table(t, buf, data_page_size=2048, use_dictionary=False,
                   compression="snappy")
    raw = bytearray(buf.getvalue())
    good = ParquetFile(bytes(raw))
    chunk = good.row_group(0).column(0)
    start, size = chunk.byte_range
    base = sum(1 for _ in chunk.pages_streamed(window=1 << 16))
    assert base > 10
    for trial in range(60):
        bad = bytearray(raw)
        mode = trial % 3
        if mode == 0:  # flip a byte inside the chunk's page stream
            off = start + int(rng.integers(0, size))
            bad[off] ^= 1 << int(rng.integers(0, 8))
        elif mode == 1:  # zero a small run
            off = start + int(rng.integers(0, max(size - 16, 1)))
            bad[off:off + 8] = b"\x00" * 8
        else:  # garbage a header-sized region
            off = start + int(rng.integers(0, max(size - 32, 1)))
            bad[off:off + 16] = bytes(rng.integers(0, 256, 16,
                                                   dtype=np.uint8))
        try:
            pf = ParquetFile(bytes(bad))
            for _ in pf.row_group(0).column(0).pages_streamed(
                    window=1 << 16):
                pass
            # stream may parse fine when the flip only hit payload bytes;
            # decoding then either errors or yields values — both fine
            try:
                pf.read(columns=["x"])
            except Exception:
                pass
        except Exception:
            pass  # any clean exception is acceptable; crashes are not
