"""Writable tables: DatasetWriter, manifest-level atomic commit,
snapshot-isolated readers, and crash-safe compaction (ISSUE 12).

The robustness bar under test: a crash at ANY byte of an ingest or
compaction leaves the table at the old snapshot or the new one, never
mixed; concurrent readers never observe a torn state; compaction output
is byte-equivalent to a one-shot sorted write; manifest zone maps prune
whole files with zero footer reads."""

import json
import os
import threading
import time

import numpy as np
import pyarrow as pa
import pytest

from parquet_tpu import (BackgroundCompactor, DatasetWriter, ParquetFile,
                         col, compact_table, open_table, recover_table)
from parquet_tpu.algebra.buffer import SortingColumn
from parquet_tpu.algebra.sorting import SortingWriter
from parquet_tpu.errors import CorruptedError
from parquet_tpu.format.enums import BoundaryOrder
from parquet_tpu.io.cache import cache_stats, clear_caches
from parquet_tpu.io.faults import SharedCrashState, table_crash_check
from parquet_tpu.io.manifest import (MANIFEST_NAME, Manifest, ManifestEntry,
                                     _dec_value, _enc_value,
                                     manifest_may_match, read_manifest)
from parquet_tpu.io.writer import (WriterOptions, columns_from_arrow,
                                   schema_from_arrow)
from parquet_tpu.obs.ledger import LEDGER, ledger_snapshot
from parquet_tpu.obs.metrics import metrics_snapshot


def _make_table(n, start=0, seed=0):
    rng = np.random.default_rng(seed)
    k = np.arange(start, start + n, dtype=np.int64)
    rng.shuffle(k)  # ingest order is NOT sorted: sorting must happen
    v = k.astype(np.float64) * 0.5
    s = [f"s{int(x) % 97:04d}" for x in k]
    return pa.table({"k": pa.array(k), "v": pa.array(v),
                     "s": pa.array(s)})


_SCHEMA = schema_from_arrow(_make_table(4).schema)
_SORT = [SortingColumn("k")]
_OPTS = WriterOptions(compression="snappy", data_page_size=4096,
                      row_group_size=1 << 16)


def _writer(d, **kw):
    kw.setdefault("sorting", _SORT)
    kw.setdefault("options", _OPTS)
    kw.setdefault("rows_per_file", 1 << 20)
    return DatasetWriter(d, _SCHEMA, **kw)


def _read_sorted(d):
    """Whole-table contents sorted by k (snapshot-order independent)."""
    arr = open_table(d).read().to_arrow()
    order = np.argsort(arr.column("k").to_numpy(), kind="stable")
    return arr.take(pa.array(order))


# ---------------------------------------------------------------------------
# manifest mechanics
# ---------------------------------------------------------------------------


def test_value_codec_round_trip():
    for v in (None, True, False, 0, -5, 1 << 80, 3.5, float("inf"),
              -0.0, b"", b"\x00\xffbytes", np.int64(7), np.float64(2.5)):
        got = _dec_value(_enc_value(v))
        if v is None:
            assert got is None
        elif isinstance(v, float):
            assert got == float(v) and isinstance(got, float)
        elif isinstance(v, (bytes, np.floating)) or not hasattr(v, "item"):
            assert got == (bytes(v) if isinstance(v, bytes) else v)
        else:
            assert got == v.item() if hasattr(v, "item") else v
    # unknown tags decode to None (inconclusive), never raise
    assert _dec_value({"t": "zz", "v": 1}) is None
    assert _dec_value("garbage") is None


def test_manifest_round_trip_and_corrupt(tmp_path):
    m = Manifest(version=3, created=1234,
                 sorting=[("k", False, True)],
                 files=[ManifestEntry("part-a.parquet", 10, 999,
                                      {"k": (1, 9, 0, 10),
                                       "s": (b"a", b"z", None, None)})])
    m2 = Manifest.deserialize(m.serialize())
    assert m2.version == 3 and m2.sorting == [("k", False, True)]
    assert m2.files[0].zone_maps["k"] == (1, 9, 0, 10)
    assert m2.files[0].zone_maps["s"] == (b"a", b"z", None, None)
    with pytest.raises(CorruptedError):
        Manifest.deserialize(b"{ torn json")
    # a torn manifest on disk is loud corruption, not a silent empty table
    (tmp_path / "t").mkdir()
    (tmp_path / "t" / MANIFEST_NAME).write_bytes(b"\x00\x01")
    with pytest.raises(CorruptedError):
        read_manifest(tmp_path / "t")


def test_serialized_form_is_byte_deterministic():
    m = Manifest(version=1, created=7, sorting=[("k", False, False)],
                 files=[ManifestEntry("part-x.parquet", 5, 50,
                                      {"k": (0, 4, 0, 5)})])
    assert m.serialize() == m.serialize()
    doc = json.loads(m.serialize())
    assert doc["version"] == 1 and doc["format"] == 1


def test_open_table_without_manifest_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        open_table(tmp_path)


# ---------------------------------------------------------------------------
# ingest + commit
# ---------------------------------------------------------------------------


def test_ingest_commit_read_parity(tmp_path):
    d = str(tmp_path / "t")
    t = _make_table(5000)
    with _writer(d) as w:
        w.write_arrow(t)
        m = w.commit()
    assert m.version == 1 and len(m.files) == 1
    got = _read_sorted(d)
    want = t.take(pa.array(np.argsort(t.column("k").to_numpy())))
    assert got.equals(want)
    # the committed snapshot knows its row count without opening parts
    assert read_manifest(d).num_rows == 5000


def test_commits_are_additive_and_versioned(tmp_path):
    d = str(tmp_path / "t")
    w = _writer(d)
    w.write_arrow(_make_table(1000, start=0))
    m1 = w.commit()
    w.write_arrow(_make_table(1000, start=1000))
    m2 = w.commit()
    w.close()
    assert (m1.version, m2.version) == (1, 2)
    assert len(m2.files) == 2
    assert m2.names()[0] == m1.names()[0]  # earlier parts keep position
    assert open_table(d).read().to_arrow().num_rows == 2000
    # empty commit is a no-op: no version churn
    w2 = _writer(d)
    m3 = w2.commit()
    w2.close()
    assert m3.version == 2


def test_rows_per_file_shards_parts(tmp_path):
    d = str(tmp_path / "t")
    with _writer(d, rows_per_file=300) as w:
        for i in range(0, 1200, 200):
            w.write_arrow(_make_table(200, start=i))
    m = read_manifest(d)
    assert len(m.files) >= 3
    assert sum(e.num_rows for e in m.files) == 1200
    assert open_table(d).read().to_arrow().num_rows == 1200


def test_committed_parts_are_sorted_with_declared_order(tmp_path):
    d = str(tmp_path / "t")
    with _writer(d) as w:
        w.write_arrow(_make_table(4000))
    m = read_manifest(d)
    pf = ParquetFile(os.path.join(d, m.files[0].name))
    ks = pf.read(columns=["k"]).columns["k"].values
    assert np.all(np.diff(np.asarray(ks)) >= 0)
    # footer declares the sort (the lookup fast path's gate) and the page
    # index carries ascending boundary_order (sorted ingestion's payoff)
    sc = pf.row_groups[0].sorting_columns
    assert sc and sc[0].column_idx == pf.schema.leaf("k").column_index
    ci = pf.row_groups[0].column("k").column_index()
    assert BoundaryOrder(ci.boundary_order) == BoundaryOrder.ASCENDING
    pf.close()


def test_key_partitioned_ingest(tmp_path):
    d = str(tmp_path / "t")
    t = _make_table(4000)
    with _writer(d, partition_on="k", num_partitions=4,
                 rows_per_file=100_000) as w:
        w.write_arrow(t)
        w.flush()
        # a key-partitioned flush emits one part per non-empty partition
        assert 2 <= len(w._flushed) <= 4
    got = _read_sorted(d)
    want = t.take(pa.array(np.argsort(t.column("k").to_numpy())))
    assert got.equals(want)
    # duplicate keys co-locate: every key's rows live in exactly one part
    d2 = str(tmp_path / "t2")
    dup = pa.table({"k": pa.array(np.tile(np.arange(50, dtype=np.int64),
                                          40)),
                    "v": pa.array(np.zeros(2000)),
                    "s": pa.array(["x"] * 2000)})
    with _writer(d2, partition_on="k", num_partitions=4,
                 rows_per_file=100_000) as w:
        w.write_arrow(dup)
    ds = open_table(d2)
    per_file_keys = [set(np.asarray(
        pf.read(columns=["k"]).columns["k"].values).tolist())
        for pf in ds.files]
    for a in range(len(per_file_keys)):
        for b in range(a + 1, len(per_file_keys)):
            assert not (per_file_keys[a] & per_file_keys[b])


def test_partition_on_rejects_unsupported_columns(tmp_path):
    with pytest.raises(ValueError):
        w = _writer(str(tmp_path / "t"), partition_on="s")
        w.write_arrow(_make_table(10))


def test_abort_removes_uncommitted_parts_and_drains_ledger(tmp_path):
    d = str(tmp_path / "t")
    acct = LEDGER.account("table.pending")
    base = acct.resident
    w = _writer(d, rows_per_file=100)
    w.write_arrow(_make_table(150))          # flushes part 1
    w.write_arrow(_make_table(100, start=150))  # flushes part 2
    w.write_arrow(_make_table(50, start=250))   # stays buffered
    assert len(w._flushed) == 2
    assert acct.resident > base  # the 50-row remainder is accounted
    w.abort()
    assert acct.resident == base
    assert not [f for f in os.listdir(d) if f.endswith(".parquet")]
    assert read_manifest(d) is None


def test_pending_ledger_is_byte_exact_and_drains(tmp_path):
    d = str(tmp_path / "t")
    acct = LEDGER.account("table.pending")
    base = acct.resident
    w = _writer(d)
    t = _make_table(1000)
    w.write_arrow(t)
    from parquet_tpu.dataset_writer import _cols_nbytes

    want = _cols_nbytes(columns_from_arrow(t, _SCHEMA))
    assert acct.resident - base == want == w.pending_bytes()
    w.write_arrow(_make_table(500, start=1000))
    assert acct.resident - base == w.pending_bytes()
    w.commit()
    assert acct.resident == base and w.pending_bytes() == 0
    w.close()


# ---------------------------------------------------------------------------
# manifest zone-map pruning
# ---------------------------------------------------------------------------


def test_prune_uses_manifest_zone_maps_zero_opens(tmp_path):
    d = str(tmp_path / "t")
    w = _writer(d)
    for i in range(4):
        w.write_arrow(_make_table(1000, start=i * 1000))
        w.commit()
    w.close()
    ds = open_table(d, pin=False)
    assert ds.snapshot_version == 4
    opened = []
    real_file = ds.file

    def spy(i):
        opened.append(i)
        return real_file(i)

    ds.file = spy
    keep = ds.prune(where=col("k").between(3200, 3600))
    assert len(keep) == 1 and keep[0].endswith(read_manifest(d).names()[3])
    # files 1 and 2 were dropped by the manifest alone: never opened, so
    # zero footer preads for them (file 0 opens once to prepare the tree)
    assert set(opened) <= {0, 3}
    # parity: the pruned scan still answers exactly
    got = ds.scan(where=col("k").between(3200, 3600), columns=["v"])
    assert len(got["v"]) == 401


def test_manifest_prune_is_conservative_on_unknown(tmp_path):
    e = ManifestEntry("p", 10, 100, {})  # no zone maps at all
    expr = col("k") == 5
    from parquet_tpu.algebra.expr import prepare

    assert manifest_may_match(e, prepare(expr, _SCHEMA)) is True
    e2 = ManifestEntry("p", 10, 100, {"k": (None, None, None, None)})
    assert manifest_may_match(e2, prepare(col("k") == 5, _SCHEMA)) is True


def test_scan_and_lookup_parity_on_table(tmp_path):
    d = str(tmp_path / "t")
    t = _make_table(6000)
    with _writer(d, rows_per_file=2000) as w:
        w.write_arrow(t)
    ds = open_table(d)
    got = ds.scan(where=(col("k") >= 100) & (col("k") <= 300),
                  columns=["v"])
    np.testing.assert_allclose(np.sort(got["v"]),
                               np.arange(100, 301) * 0.5)
    res = ds.find_rows("k", [5, 4321, 10**9], columns=["v"])
    assert res[0].num_rows == 1 and res[0].values["v"][0] == 2.5
    assert res[1].num_rows == 1 and res[1].values["v"][0] == 4321 * 0.5
    assert res[2].num_rows == 0
    # sorted parts drive the in-page binary search fast path
    assert res.counters["binary_search_hits"] > 0


# ---------------------------------------------------------------------------
# compaction
# ---------------------------------------------------------------------------


def test_compaction_byte_equivalent_to_one_shot(tmp_path):
    d = str(tmp_path / "t")
    t = _make_table(5000, seed=3)
    w = _writer(d, rows_per_file=1000)
    for i in range(0, 5000, 1000):
        w.write_arrow(t.slice(i, 1000))
        w.commit()
    w.close()
    assert len(read_manifest(d).files) == 5
    m = compact_table(d)
    assert m is not None and len(m.files) == 1
    # one-shot SortingWriter write of the same rows, same options
    one = str(tmp_path / "oneshot.parquet")
    sw = SortingWriter(one, _SCHEMA, _SORT, _OPTS)
    sw.write(columns_from_arrow(t, _SCHEMA), t.num_rows)
    sw.close()
    got = open_table(d).read().to_arrow()
    want = ParquetFile(one).read().to_arrow()
    assert got.equals(want)  # rows AND order identical
    # replaced parts are gone from disk; only the merged part remains
    parts = [f for f in os.listdir(d) if f.endswith(".parquet")]
    assert parts == [m.files[0].name]


def test_compaction_max_files_folds_smallest(tmp_path):
    d = str(tmp_path / "t")
    w = _writer(d)
    for n in (100, 2000, 150):
        w.write_arrow(_make_table(n, start=0, seed=n))
        w.commit()
    w.close()
    m = compact_table(d, max_files=2)
    assert len(m.files) == 2
    sizes = sorted(e.num_rows for e in m.files)
    assert sizes == [250, 2000]  # the two small parts folded


def test_compaction_conflict_aborts_cleanly(tmp_path):
    d = str(tmp_path / "t")
    w = _writer(d)
    for i in range(3):
        w.write_arrow(_make_table(500, start=i * 500))
        w.commit()
    w.close()
    m0 = metrics_snapshot()["counters"].get("table.commit_conflicts", 0)
    # rival: between the merge and the commit, a compaction removes an
    # input.  Simulate by compacting FIRST, then replaying a commit whose
    # victims no longer exist.
    live = read_manifest(d)
    from parquet_tpu.io.manifest import commit_manifest

    got = compact_table(d)
    assert got is not None

    def stale_mutate(cur):
        names = set(cur.names())
        if not {e.name for e in live.files} <= names:
            return None  # what compact_table's mutate does on conflict
        return cur

    assert commit_manifest(d, stale_mutate) is None
    # the real conflict path end-to-end: patch read_manifest timing is
    # overkill; assert instead that a second compaction of ONE file no-ops
    assert compact_table(d) is None
    assert read_manifest(d).version == got.version
    assert metrics_snapshot()["counters"].get(
        "table.commit_conflicts", 0) >= m0


def test_compaction_commit_invalidates_caches(tmp_path):
    d = str(tmp_path / "t")
    w = _writer(d)
    for i in range(2):
        w.write_arrow(_make_table(1000, start=i * 1000))
        w.commit()
    w.close()
    clear_caches()
    ds = open_table(d)
    ds.read()  # warm footer + chunk caches for both parts
    st = cache_stats()
    assert st.footer_entries >= 2 and st.chunk_entries > 0
    old_paths = list(ds.paths)
    compact_table(d)
    from parquet_tpu.io.cache import CHUNKS, FOOTERS

    for p in old_paths:
        ap = os.path.abspath(p)
        assert not [k for k in FOOTERS._entries if k[0] == ap]
        assert not [k for k in CHUNKS._entries if k[0][0] == ap]
    # a post-commit open sees the new snapshot
    ds2 = open_table(d)
    assert ds2.snapshot_version == ds.snapshot_version + 1
    assert ds2.read().to_arrow().num_rows == 2000


def test_background_compactor(tmp_path):
    d = str(tmp_path / "t")
    w = _writer(d)
    for i in range(5):
        w.write_arrow(_make_table(200, start=i * 200))
        w.commit()
    w.close()
    with BackgroundCompactor(d, interval_s=0.05, min_files=2) as bc:
        deadline = time.time() + 10
        while time.time() < deadline:
            m = read_manifest(d)
            if len(m.files) == 1:
                break
            time.sleep(0.05)
    assert len(read_manifest(d).files) == 1
    assert bc.passes >= 1
    assert open_table(d).read().to_arrow().num_rows == 1000


# ---------------------------------------------------------------------------
# snapshot isolation
# ---------------------------------------------------------------------------


def test_snapshot_pinned_reader_survives_compaction(tmp_path):
    d = str(tmp_path / "t")
    w = _writer(d)
    for i in range(3):
        w.write_arrow(_make_table(800, start=i * 800))
        w.commit()
    ds = open_table(d)  # pinned: fds held on all 3 parts
    before = ds.read().to_arrow()
    # a writer commits AND a compaction replaces every pinned part
    w.write_arrow(_make_table(800, start=2400))
    w.commit()
    compact_table(d)
    w.close()
    assert [f for f in os.listdir(d) if f.endswith(".parquet")] \
        and len(read_manifest(d).files) == 1
    # the pinned reader still drains ITS snapshot, byte-identically
    again = ds.read().to_arrow()
    assert again.equals(before) and again.num_rows == 2400
    # lookups on the pinned snapshot too
    res = ds.find_rows("k", [100], columns=["v"])
    assert res[0].num_rows == 1
    # a fresh open sees the new world
    ds2 = open_table(d)
    assert ds2.read().to_arrow().num_rows == 3200
    assert ds2.snapshot_version > ds.snapshot_version


def test_concurrent_ingest_scan_lookup_compact_hammer(tmp_path):
    """Snapshot isolation under an 8-worker hammer: one ingest thread
    commits batches in order, a compactor folds continuously, and reader
    threads (whole reads, filtered scans, keyed lookups) must only ever
    observe a PREFIX of committed batches — all-or-nothing, never a torn
    part or a half-commit."""
    d = str(tmp_path / "t")
    B, NB = 400, 10
    errors: list = []
    stop = threading.Event()
    committed = threading.Event()

    def ingester():
        try:
            w = _writer(d, rows_per_file=B)
            for j in range(NB):
                w.write_arrow(_make_table(B, start=j * B))
                w.commit()
                committed.set()
            w.close()
        except Exception as e:  # pragma: no cover - failure surface
            errors.append(("ingest", e))
        finally:
            stop.set()

    def reader():
        try:
            committed.wait(30)
            while not stop.is_set():
                ds = open_table(d)
                arr = ds.read().to_arrow()
                n = arr.num_rows
                assert n % B == 0 and n > 0, n
                ks = np.sort(arr.column("k").to_numpy())
                np.testing.assert_array_equal(ks, np.arange(n))
                ds.close()
        except Exception as e:  # pragma: no cover
            errors.append(("read", e))

    def scanner():
        try:
            committed.wait(30)
            while not stop.is_set():
                ds = open_table(d)
                got = ds.scan(where=col("k").between(0, B - 1),
                              columns=["v"])
                np.testing.assert_allclose(np.sort(got["v"]),
                                           np.arange(B) * 0.5)
                ds.close()
        except Exception as e:  # pragma: no cover
            errors.append(("scan", e))

    def looker():
        try:
            committed.wait(30)
            while not stop.is_set():
                ds = open_table(d)
                res = ds.find_rows("k", [7, B - 1], columns=["v"])
                assert res[0].num_rows == 1
                assert res[0].values["v"][0] == 3.5
                ds.close()
        except Exception as e:  # pragma: no cover
            errors.append(("lookup", e))

    def compactor():
        try:
            committed.wait(30)
            while not stop.is_set():
                compact_table(d)
        except Exception as e:  # pragma: no cover
            errors.append(("compact", e))

    threads = [threading.Thread(target=f) for f in
               (ingester, compactor, reader, reader, scanner, scanner,
                looker, looker)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not errors, errors
    # quiesce: final state is every batch, once
    compact_table(d)
    arr = open_table(d).read().to_arrow()
    np.testing.assert_array_equal(np.sort(arr.column("k").to_numpy()),
                                  np.arange(B * NB))
    # recovery after the storm sweeps nothing live
    swept = recover_table(d)
    assert open_table(d).read().to_arrow().num_rows == B * NB, swept


# ---------------------------------------------------------------------------
# crash safety
# ---------------------------------------------------------------------------


def _setup_base(d):
    with _writer(d) as w:
        w.write_arrow(_make_table(600))


def test_manifest_crash_matrix_ingest(tmp_path):
    def ingest(d, wrap):
        w = _writer(d, rows_per_file=300, _sink_wrap=wrap)
        w.write_arrow(_make_table(600, start=600))
        w.commit()

    res = table_crash_check(_setup_base, ingest, str(tmp_path),
                            samples=10, seed=7)
    outcomes = {r["outcome"] for r in res}
    assert outcomes == {"old", "new"}
    # the commit-rename boundary itself was sampled (offset == total)
    offs = [r["offset"] for r in res]
    assert max(offs) - 1 in offs


def test_manifest_crash_matrix_compaction(tmp_path):
    def setup(d):
        w = _writer(d, rows_per_file=200)
        for i in range(3):
            w.write_arrow(_make_table(200, start=i * 200))
        w.commit()
        w.close()
        assert len(read_manifest(d).files) >= 2

    def ingest(d, wrap):
        if compact_table(d, _sink_wrap=wrap) is None:
            raise AssertionError("compaction did not commit")

    res = table_crash_check(setup, ingest, str(tmp_path), samples=8,
                            seed=11)
    assert {r["outcome"] for r in res} == {"old", "new"}


def test_shared_crash_state_covers_multiple_sinks(tmp_path):
    from parquet_tpu.io.faults import InjectedWriterCrash
    from parquet_tpu.io.sink import AtomicFileSink

    state = SharedCrashState(crash_at_byte=10)
    s1 = state.wrap(AtomicFileSink(str(tmp_path / "a")))
    s2 = state.wrap(AtomicFileSink(str(tmp_path / "b")))
    s1.write(b"123456")
    with pytest.raises(InjectedWriterCrash):
        s2.write(b"789abcdef")  # crosses the SHARED budget at byte 10
    assert state.crashed
    with pytest.raises(InjectedWriterCrash):
        s1.write(b"x")  # every sink is dead after the crash
    with pytest.raises(InjectedWriterCrash):
        s1.close()
    # dead-process abort: fd released, temp file LEFT for recovery
    s1.abort()
    s2.abort()
    assert [f for f in os.listdir(tmp_path) if f.endswith(".tmp")]


def test_sweep_spares_inflight_uncommitted_parts(tmp_path):
    """A sweep racing the flush→commit window must not eat parts the
    very next manifest rename publishes (review finding: the window
    where a flushed part is on disk but in no manifest)."""
    d = str(tmp_path / "t")
    w = _writer(d, rows_per_file=100)
    w.write_arrow(_make_table(100))  # flushed part, NOT yet committed
    assert len(w._flushed) == 1
    assert recover_table(d) == []  # the live writer shields its part
    m = w.commit()
    assert m.num_rows == 100
    w.close()
    assert open_table(d).read().to_arrow().num_rows == 100
    # once the writer is gone, the same on-disk state IS an orphan
    w2 = _writer(d, rows_per_file=100)
    w2.write_arrow(_make_table(100, start=100))
    stranded = list(w2._flushed)
    w2._closed = True  # simulate death without cleanup
    swept = recover_table(d)
    assert stranded and set(stranded) <= set(swept)


def test_sorted_fast_path_uint64_keys_above_2_53(tmp_path):
    """Review finding: a python-int needle against a uint64 array
    promotes to float64 in searchsorted, collapsing keys above 2^53 —
    the typed-needle fix must keep the fast path exact."""
    d = str(tmp_path / "t")
    base = 1 << 60
    k = pa.array(np.arange(base, base + 2000, dtype=np.uint64))
    t = pa.table({"k": k, "v": pa.array(np.arange(2000,
                                                  dtype=np.float64))})
    schema = schema_from_arrow(t.schema)
    w = DatasetWriter(d, schema, sorting=[SortingColumn("k")],
                      options=_OPTS)
    w.write_arrow(t)
    w.commit()
    w.close()
    ds = open_table(d)
    res = ds.find_rows("k", [base + 3, base + 1999, base + 5000],
                       columns=["v"])
    assert res[0].num_rows == 1 and res[0].values["v"][0] == 3.0
    assert res[1].num_rows == 1 and res[1].values["v"][0] == 1999.0
    assert res[2].num_rows == 0
    assert res.counters["binary_search_hits"] > 0  # fast path, not mask


def test_recover_sweeps_orphans_only(tmp_path):
    d = str(tmp_path / "t")
    with _writer(d) as w:
        w.write_arrow(_make_table(500))
    live = read_manifest(d).names()
    # a dead writer's leavings: a stray temp and an uncommitted part
    (tmp_path / "t" / "part-deadbeef00000000.parquet").write_bytes(b"torn")
    (tmp_path / "t" / f"{live[0]}.123abc.tmp").write_bytes(b"half")
    swept = recover_table(d)
    assert sorted(swept) == sorted(["part-deadbeef00000000.parquet",
                                    f"{live[0]}.123abc.tmp"])
    assert sorted(f for f in os.listdir(d) if f != MANIFEST_NAME) == \
        sorted(live)
    assert open_table(d).read().to_arrow().num_rows == 500
    assert metrics_snapshot()["counters"]["table.orphans_swept"] >= 2


# ---------------------------------------------------------------------------
# observability
# ---------------------------------------------------------------------------


def test_table_metrics_and_debugz(tmp_path):
    d = str(tmp_path / "t")
    c0 = metrics_snapshot()["counters"]
    w = _writer(d)
    w.write_arrow(_make_table(1000))
    from parquet_tpu.obs import debugz_snapshot

    dz = debugz_snapshot()
    mine = [t for t in dz["tables"]["writers"] if t["dir"] == d]
    assert mine and mine[0]["pending_rows"] == 1000
    assert mine[0]["pending_bytes"] == w.pending_bytes() > 0
    w.commit()
    w.close()
    compact_table(d)  # no-op (1 file) but must not throw
    c1 = metrics_snapshot()["counters"]
    assert c1["table.commits"] - c0.get("table.commits", 0) == 1
    assert c1["table.rows_ingested"] - c0.get("table.rows_ingested", 0) \
        == 1000
    assert c1["table.files_written"] - c0.get("table.files_written", 0) \
        == 1
    h = metrics_snapshot()["histograms"]["table.commit_s"]
    assert h["count"] >= 1
    # ledger account is pre-declared and drained
    led = ledger_snapshot()["accounts"]["table.pending"]
    assert led["resident_bytes"] == 0 and led["high_water_bytes"] > 0
    # a closed writer leaves the /debugz table section
    dz2 = debugz_snapshot()
    assert not [t for t in dz2["tables"]["writers"] if t["dir"] == d]


def test_prom_families_render(tmp_path):
    from parquet_tpu.obs.export import render_prometheus

    prom = render_prometheus()
    for fam in ("parquet_tpu_table_commits_total",
                "parquet_tpu_table_compactions_total",
                "parquet_tpu_table_orphans_swept_total",
                "parquet_tpu_lookup_binary_search_hits_total",
                "parquet_tpu_lookup_key_shards_total"):
        assert any(line.startswith(fam + " ")
                   for line in prom.splitlines()), fam
    assert 'account="table.pending"' in prom


# ---------------------------------------------------------------------------
# satellite: key-batch sharding + NOT IN probe on tables
# ---------------------------------------------------------------------------


def test_key_shard_lookup_parity(tmp_path, monkeypatch):
    d = str(tmp_path / "t")
    n = 20000
    with _writer(d, rows_per_file=n) as w:
        w.write_arrow(_make_table(n, seed=9))
    ds = open_table(d)
    rng = np.random.default_rng(1)
    keys = [int(x) for x in rng.integers(0, n + 50, 400)]
    base = ds.find_rows("k", keys, columns=["v"])
    monkeypatch.setenv("PARQUET_TPU_LOOKUP_KEY_SHARD", "50")
    sharded = ds.find_rows("k", keys, columns=["v"])
    assert sharded.counters["key_shards"] >= 2
    for h1, h2 in zip(base, sharded):
        assert list(h1.rows) == list(h2.rows)
        np.testing.assert_array_equal(h1.values["v"], h2.values["v"])
    # off switch
    monkeypatch.setenv("PARQUET_TPU_LOOKUP_KEY_SHARD", "0")
    off = ds.find_rows("k", keys)
    assert off.counters["key_shards"] == 0


def test_not_in_coverage_prunes_row_groups(tmp_path):
    from parquet_tpu.io.planner import ScanPlanner, _not_in_covers
    from parquet_tpu.parallel.host_scan import scan_expr

    assert _not_in_covers([3, 4, 5, 6], 4, 6)
    assert _not_in_covers([3, 4, 5, 6], 3, 6)
    assert not _not_in_covers([3, 4, 6], 3, 6)  # gap at 5
    assert not _not_in_covers([3.0, 4.0], 3.0, 4.0)  # floats: uncountable
    assert _not_in_covers([b"xy"], b"xy", b"xy")  # constant page, any type
    n = 8000
    codes = np.repeat(np.arange(8, dtype=np.int64), n // 8)
    t = pa.table({"c": pa.array(codes),
                  "v": pa.array(np.arange(n, dtype=np.float64))})
    p = str(tmp_path / "codes.parquet")
    from parquet_tpu.io.writer import write_table

    write_table(t, p, WriterOptions(compression="snappy",
                                    row_group_size=n // 4,
                                    data_page_size=2048))
    pf = ParquetFile(p)
    expr = ~col("c").isin([0, 1, 2, 3])  # covers rgs 0-1 entirely
    plan = ScanPlanner(pf).plan(expr)
    assert plan.counters["rg_pruned_stats"] == 2
    got = scan_expr(pf, expr, columns=["v"])
    np.testing.assert_array_equal(got["v"],
                                  np.arange(n, dtype=np.float64)[codes > 3])
    pf.close()


def test_lookup_fast_path_with_nulls(tmp_path):
    d = str(tmp_path / "t")
    n = 3000
    k = np.arange(n, dtype=np.int64)
    mask = k % 7 == 0
    karr = pa.array(np.where(mask, 0, k), mask=mask)
    t = pa.table({"k": karr,
                  "v": pa.array(np.arange(n, dtype=np.float64)),
                  "s": pa.array(["x"] * n)})
    with _writer(d, rows_per_file=n) as w:
        w.write_arrow(t)
    ds = open_table(d)
    res = ds.find_rows("k", [8, 14, 100], columns=["v"])
    # 14 is NULL in the source: NULL never matches a key
    assert res[0].num_rows == 1 and res[1].num_rows == 0
    assert res[2].num_rows == 1
    assert res.counters["binary_search_hits"] > 0
