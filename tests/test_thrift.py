"""L0 tests: compact protocol against pyarrow-written footers + self round-trip."""

import io
import struct

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from parquet_tpu.format import enums, metadata as md, thrift


def _pyarrow_file_bytes(**write_kwargs) -> bytes:
    t = pa.table(
        {
            "a": pa.array(np.arange(100, dtype=np.int64)),
            "b": pa.array(np.linspace(0, 1, 100)),
            "s": pa.array([f"s{i % 5}" for i in range(100)]),
            "opt": pa.array([None if i % 3 == 0 else i for i in range(100)], type=pa.int32()),
        }
    )
    buf = io.BytesIO()
    pq.write_table(t, buf, **write_kwargs)
    return buf.getvalue()


def _footer(raw: bytes) -> md.FileMetaData:
    flen = struct.unpack("<I", raw[-8:-4])[0]
    fmd, consumed = thrift.deserialize(md.FileMetaData, raw[-8 - flen : -8])
    assert consumed == flen  # every byte accounted for
    return fmd


@pytest.mark.parametrize("compression", ["none", "snappy", "zstd", "gzip"])
def test_footer_parses(compression):
    raw = _pyarrow_file_bytes(compression=compression)
    fmd = _footer(raw)
    assert fmd.num_rows == 100
    assert len(fmd.row_groups) == 1
    assert len(fmd.row_groups[0].columns) == 4
    names = [s.name for s in fmd.schema[1:]]
    assert names == ["a", "b", "s", "opt"]


def test_footer_with_page_index():
    raw = _pyarrow_file_bytes(write_page_index=True)
    fmd = _footer(raw)
    col = fmd.row_groups[0].columns[0]
    assert col.column_index_offset is not None
    ci, _ = thrift.deserialize(md.ColumnIndex, raw, col.column_index_offset)
    assert ci.null_pages == [False]
    assert ci.min_values is not None and ci.max_values is not None
    oi, _ = thrift.deserialize(md.OffsetIndex, raw, col.offset_index_offset)
    assert oi.page_locations[0].first_row_index == 0


def test_page_header_parses():
    raw = _pyarrow_file_bytes(compression="snappy")
    fmd = _footer(raw)
    m = fmd.row_groups[0].columns[0].meta_data
    off = m.dictionary_page_offset if m.dictionary_page_offset is not None else m.data_page_offset
    ph, _ = thrift.deserialize(md.PageHeader, raw, off)
    assert ph.type in (int(enums.PageType.DATA_PAGE), int(enums.PageType.DICTIONARY_PAGE),
                       int(enums.PageType.DATA_PAGE_V2))
    assert ph.compressed_page_size > 0


def test_roundtrip_serialize():
    raw = _pyarrow_file_bytes(write_page_index=True, compression="zstd")
    fmd = _footer(raw)
    blob = thrift.serialize(fmd)
    fmd2, consumed = thrift.deserialize(md.FileMetaData, blob)
    assert consumed == len(blob)
    assert fmd2.num_rows == fmd.num_rows
    assert len(fmd2.schema) == len(fmd.schema)
    for a, b in zip(fmd.schema, fmd2.schema):
        assert (a.name, a.type, a.repetition_type, a.converted_type) == (
            b.name, b.type, b.repetition_type, b.converted_type)
    m1 = fmd.row_groups[0].columns[2].meta_data
    m2 = fmd2.row_groups[0].columns[2].meta_data
    assert m1.path_in_schema == m2.path_in_schema
    assert m1.statistics.min_value == m2.statistics.min_value


def test_unknown_fields_skipped():
    # a struct with extra fields our spec doesn't know: craft KeyValue + extras
    w = thrift.CompactWriter()
    # field 1 (string "k"), unknown field 5 (i64), unknown field 6 (list<i32>), field 2 (string "v")
    w.out.append((1 << 4) | 0x08)
    w.write_bytes(b"k")
    w.out.append((4 << 4) | 0x06)
    w.write_zigzag(123456789)
    w.out.append((1 << 4) | 0x09)
    w.out.append((3 << 4) | 0x05)
    for x in (1, 2, 3):
        w.write_zigzag(x)
    # field 2 via long-form header (delta 0 escape)
    w.out.append(0x08)
    w.write_zigzag(2)
    w.write_bytes(b"v")
    w.out.append(0x00)
    kv, consumed = thrift.deserialize(md.KeyValue, w.getvalue())
    assert consumed == len(w.getvalue())
    assert kv.key == "k" and kv.value == "v"


def test_zigzag_edge_values():
    for n in [0, -1, 1, 2**31 - 1, -(2**31), 2**63 - 1, -(2**63)]:
        w = thrift.CompactWriter()
        w.write_zigzag(n)
        r = thrift.CompactReader(w.getvalue())
        assert r.read_zigzag() == n
