"""Typed API tests: dataclass schema inference, write/read round-trips
(the reference's canonical random-struct round-trip pattern, SURVEY.md §4.1)."""

import dataclasses
import datetime
import io
from typing import List, Optional

import numpy as np
import pyarrow.parquet as pq
import pytest

from parquet_tpu import ParquetFile, WriterOptions
from parquet_tpu.format.enums import Type
from parquet_tpu.typed import (TypedReader, TypedWriter, read_objects,
                               read_pytree, schema_of, write_objects)


@dataclasses.dataclass
class Order:
    order_id: int
    price: float
    comment: str
    flagged: bool
    discount: Optional[float]
    quantities: List[int]
    tag: Optional[str]


@dataclasses.dataclass
class Address:
    city: str
    zip_code: int


@dataclasses.dataclass
class Customer:
    name: str
    address: Address
    score: Optional[int]


def _orders(n=500):
    rng = np.random.default_rng(5)
    return [
        Order(
            order_id=int(i),
            price=float(rng.random() * 100),
            comment=f"comment-{i % 37}",
            flagged=bool(i % 3 == 0),
            discount=None if i % 4 == 0 else float(i % 10) / 10,
            quantities=[int(x) for x in rng.integers(0, 50, i % 5)],
            tag=None if i % 2 else f"tag{i % 7}",
        )
        for i in range(n)
    ]


def test_schema_of():
    s = schema_of(Order)
    assert [l.dotted_path for l in s.leaves] == [
        "order_id", "price", "comment", "flagged", "discount",
        "quantities.list.element", "tag"]
    assert s.leaf("order_id").physical_type == Type.INT64
    assert s.leaf("order_id").max_definition_level == 0  # required
    assert s.leaf("discount").max_definition_level == 1
    q = s.leaf("quantities.list.element")
    # required list of required ints: one def level (the repeated node)
    assert q.max_repetition_level == 1 and q.max_definition_level == 1


def test_roundtrip_objects():
    objs = _orders()
    buf = io.BytesIO()
    write_objects(objs, buf)
    got = read_objects(buf.getvalue(), Order)
    assert got == objs


def test_typed_reader_batches():
    objs = _orders(100)
    buf = io.BytesIO()
    write_objects(objs, buf)
    r = TypedReader(buf.getvalue(), Order)
    first = r.read(30)
    rest = r.read(1000)
    assert first == objs[:30] and rest == objs[30:]


def test_nested_dataclass():
    custs = [Customer(name=f"c{i}", address=Address(city=f"city{i % 5}",
                                                    zip_code=10000 + i),
                      score=None if i % 3 == 0 else i)
             for i in range(50)]
    buf = io.BytesIO()
    write_objects(custs, buf)
    got = read_objects(buf.getvalue(), Customer)
    assert got == custs
    # pyarrow can read the nested file too
    t = pq.read_table(io.BytesIO(buf.getvalue()))
    assert t.num_rows == 50
    assert t["address"][0].as_py() == {"city": "city0", "zip_code": 10000}


def test_dates_and_datetimes():
    @dataclasses.dataclass
    class Event:
        day: datetime.date
        at: datetime.datetime

    evs = [Event(day=datetime.date(2020, 1, 1) + datetime.timedelta(days=i),
                 at=datetime.datetime(2020, 1, 1, 12, 0, i % 60,
                                      tzinfo=datetime.timezone.utc))
           for i in range(40)]
    buf = io.BytesIO()
    write_objects(evs, buf)
    got = read_objects(buf.getvalue(), Event)
    assert [e.day for e in got] == [e.day for e in evs]
    assert [e.at for e in got] == [e.at for e in evs]


def test_read_pytree():
    objs = _orders(200)
    buf = io.BytesIO()
    write_objects(objs, buf)
    tree = read_pytree(buf.getvalue(), device=False)
    assert "order_id" in tree and "price" in tree
    vals = np.asarray(tree["order_id"])
    if vals.ndim == 2:  # device pair representation
        vals = np.ascontiguousarray(vals).view(np.int64).reshape(-1)
    np.testing.assert_array_equal(vals, np.arange(200))


def test_typed_reader_streams_batches():
    """read(n) must stream through the bounded iterator, not materialize the
    file: draining in odd-sized chunks equals read_all, and the first read
    must not have touched the tail row group's pages."""
    import dataclasses
    import io as _io

    @dataclasses.dataclass
    class Rec:
        a: int
        b: str

    objs = [Rec(a=i, b=f"s{i % 97}") for i in range(30000)]
    buf = _io.BytesIO()
    write_objects(objs, buf, options=WriterOptions(row_group_size=7000,
                                                   data_page_size=4096))
    raw = buf.getvalue()
    assert len(ParquetFile(raw).row_groups) == 5  # write() splits groups

    r = TypedReader(raw, Rec, batch_rows=1000)
    got = []
    while True:
        part = r.read(777)
        if not part:
            break
        got.append(part)
    flat = [x for p in got for x in p]
    assert flat == objs
    assert all(len(p) == 777 for p in got[:-1])

    # boundedness: after reading only 500 rows, later row groups untouched
    pf = ParquetFile(raw)
    reads = []
    orig = pf.source.pread
    pf.source.pread = lambda off, size: (reads.append(size), orig(off, size))[1]
    r2 = TypedReader(pf, Rec, batch_rows=500)
    assert len(r2.read(500)) == 500
    assert sum(reads) < len(raw) / 4
