"""Typed API: Dict[K,V] map fields and List[dataclass] repeated groups.

Reference parity: ``schema.go — SchemaOf`` maps Go ``map[K]V`` fields to the
MAP logical type and ``[]struct`` fields to repeated groups (SURVEY.md §2.1
Schema/reflection).  These tests round-trip both through the typed front end
and cross-check the file with pyarrow (the live interop oracle).
"""

import dataclasses
import io
from typing import Dict, List, Optional

import numpy as np
import pyarrow.parquet as pq
import pytest

from parquet_tpu.typed import read_objects, schema_of, write_objects


@dataclasses.dataclass
class WithMap:
    name: str
    attrs: Dict[str, int]


@dataclasses.dataclass
class WithOptMap:
    k: int
    tags: Optional[Dict[str, Optional[float]]]


@dataclasses.dataclass
class Point:
    x: float
    y: float
    label: Optional[str]


@dataclasses.dataclass
class Track:
    tid: int
    points: List[Point]


@dataclasses.dataclass
class OptTrack:
    tid: int
    points: Optional[List[Point]]


def _roundtrip(objs, cls):
    buf = io.BytesIO()
    write_objects(objs, buf, cls)
    buf.seek(0)
    return read_objects(buf, cls)


def test_schema_of_map():
    s = schema_of(WithMap)
    paths = [l.dotted_path for l in s.leaves]
    assert paths == ["name", "attrs.key_value.key", "attrs.key_value.value"]
    kv_key = s.leaf(("attrs", "key_value", "key"))
    assert kv_key.max_repetition_level == 1
    # required map + repeated group = def 1 for an empty map entry
    assert kv_key.max_definition_level == 1


def test_map_roundtrip():
    objs = [
        WithMap("a", {"x": 1, "y": 2}),
        WithMap("b", {}),
        WithMap("c", {"z": -5}),
    ]
    assert _roundtrip(objs, WithMap) == objs


def test_optional_map_with_null_values_roundtrip():
    objs = [
        WithOptMap(1, {"a": 1.5, "b": None}),
        WithOptMap(2, None),
        WithOptMap(3, {}),
        WithOptMap(4, {"c": 0.25}),
    ]
    assert _roundtrip(objs, WithOptMap) == objs


def test_map_pyarrow_interop():
    objs = [WithMap("a", {"x": 1, "y": 2}), WithMap("b", {"z": 3})]
    buf = io.BytesIO()
    write_objects(objs, buf, WithMap)
    buf.seek(0)
    tab = pq.read_table(buf)
    # pyarrow reads MAP columns as lists of (key, value) tuples
    assert tab.column("attrs").to_pylist() == [
        [("x", 1), ("y", 2)], [("z", 3)]]
    assert tab.column("name").to_pylist() == ["a", "b"]


def test_list_of_dataclass_roundtrip():
    objs = [
        Track(1, [Point(0.0, 1.0, "s"), Point(2.0, 3.0, None)]),
        Track(2, []),
        Track(3, [Point(-1.0, -2.0, "e")]),
    ]
    assert _roundtrip(objs, Track) == objs


def test_optional_list_of_dataclass_roundtrip():
    objs = [
        OptTrack(1, [Point(0.5, 1.5, None)]),
        OptTrack(2, None),
        OptTrack(3, []),
    ]
    assert _roundtrip(objs, OptTrack) == objs


def test_list_of_dataclass_pyarrow_interop():
    objs = [Track(7, [Point(1.0, 2.0, "p"), Point(3.0, 4.0, None)])]
    buf = io.BytesIO()
    write_objects(objs, buf, Track)
    buf.seek(0)
    got = pq.read_table(buf).column("points").to_pylist()
    assert got == [[{"x": 1.0, "y": 2.0, "label": "p"},
                    {"x": 3.0, "y": 4.0, "label": None}]]


def test_map_struct_value_roundtrip():
    @dataclasses.dataclass
    class Stat:
        lo: int
        hi: int

    @dataclasses.dataclass
    class WithStructMap:
        day: int
        stats: Dict[str, Stat]

    objs = [
        WithStructMap(1, {"a": Stat(0, 10), "b": Stat(-5, 5)}),
        WithStructMap(2, {}),
    ]
    assert _roundtrip(objs, WithStructMap) == objs


def test_fields_named_like_wrappers_still_work():
    @dataclasses.dataclass
    class Odd:
        list: int  # noqa: A003 - deliberately shadowing the wrapper name
        key_value: str

    objs = [Odd(1, "a"), Odd(2, "b")]
    assert _roundtrip(objs, Odd) == objs


def test_unsupported_shapes_raise():
    @dataclasses.dataclass
    class Deep:
        v: int

    @dataclasses.dataclass
    class BadElemOpt:
        xs: List[Optional[Point]]

    with pytest.raises(TypeError):
        schema_of(BadElemOpt)

    @dataclasses.dataclass
    class BadKey:
        m: Dict[bytes, Dict[str, int]]  # nested map value unsupported

    with pytest.raises(TypeError):
        schema_of(BadKey)


def test_numpy_array_list_field():
    @dataclasses.dataclass
    class Arr:
        xs: List[np.float32]

    objs = [Arr(np.array([1.0, 2.5], np.float32)), Arr(np.array([], np.float32))]
    got = _roundtrip(objs, Arr)
    assert [list(map(float, o.xs)) for o in got] == [[1.0, 2.5], []]
