"""Write-path tests: pyarrow reads our files; our reader round-trips; bloom,
page index, statistics, CRC, multi-row-group, both page versions."""

import io

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from parquet_tpu.format.enums import Encoding
from parquet_tpu.io.reader import ParquetFile, ReadOptions
from parquet_tpu.io.writer import (ColumnData, ParquetWriter, WriterOptions,
                                   schema_from_arrow, write_table)


def _write(t, **opt_kw) -> bytes:
    buf = io.BytesIO()
    write_table(t, buf, WriterOptions(**opt_kw) if opt_kw else None)
    return buf.getvalue()


def _pyarrow_equal(raw: bytes, t: pa.Table):
    got = pq.read_table(io.BytesIO(raw))
    for name in t.column_names:
        g = got[name].combine_chunks()
        e = t[name].combine_chunks()
        if g.type != e.type:
            g = g.cast(e.type)
        assert g.equals(e), f"{name}: pyarrow readback mismatch"


def _self_equal(raw: bytes, t: pa.Table, device=False):
    tab = ParquetFile(raw).read(device=device)
    for name in t.column_names:
        leafpaths = [p for p in tab.keys() if p == name or p.startswith(name + ".")]
        arr = tab[leafpaths[0]].to_arrow()
        e = t[name].combine_chunks()
        if arr.type != e.type:
            arr = arr.cast(e.type)
        assert arr.equals(e), f"{name}: self readback mismatch"


def _basic_table(rng, n=5000):
    return pa.table({
        "i64": pa.array(rng.integers(-(2**60), 2**60, n)),
        "i32": pa.array(rng.integers(-(2**31), 2**31, n).astype(np.int32)),
        "f32": pa.array(rng.random(n, dtype=np.float32)),
        "f64": pa.array(rng.random(n)),
        "b": pa.array(rng.random(n) < 0.5),
        "s": pa.array([f"string-{i % 211}" for i in range(n)]),
        "opt": pa.array([None if i % 3 == 0 else i for i in range(n)], type=pa.int64()),
    })


@pytest.mark.parametrize("compression", ["none", "snappy", "zstd", "gzip", "brotli", "lz4"])
def test_codecs_pyarrow_reads(compression, rng):
    t = _basic_table(rng)
    raw = _write(t, compression=compression)
    _pyarrow_equal(raw, t)
    _self_equal(raw, t)


@pytest.mark.parametrize("dpv", [1, 2])
def test_page_versions(dpv, rng):
    t = _basic_table(rng)
    raw = _write(t, data_page_version=dpv)
    _pyarrow_equal(raw, t)
    _self_equal(raw, t)
    _self_equal(raw, t, device=True)


def test_encodings(rng):
    t = pa.table({
        "delta": pa.array(np.sort(rng.integers(0, 2**44, 20000))),
        "delta32": pa.array(rng.integers(-(2**30), 2**30, 20000).astype(np.int32)),
        "bss": pa.array(rng.random(20000, dtype=np.float32)),
        "dlba": pa.array([f"value-{i}" for i in range(20000)]),
        "dba": pa.array([f"prefix-{i // 100:05d}-{i % 100}" for i in range(20000)]),
    })
    raw = _write(t, dictionary=False, column_encoding={
        "delta": Encoding.DELTA_BINARY_PACKED,
        "delta32": Encoding.DELTA_BINARY_PACKED,
        "bss": Encoding.BYTE_STREAM_SPLIT,
        "dlba": Encoding.DELTA_LENGTH_BYTE_ARRAY,
        "dba": Encoding.DELTA_BYTE_ARRAY,
    })
    _pyarrow_equal(raw, t)
    _self_equal(raw, t)


def test_dictionary_encoding(rng):
    t = pa.table({
        "s": pa.array([f"cat-{i % 13}" for i in range(30000)]),
        "i": pa.array(rng.integers(0, 29, 30000)),
    })
    raw = _write(t)
    pf = ParquetFile(raw)
    m = pf.metadata.row_groups[0].columns[0].meta_data
    assert int(Encoding.RLE_DICTIONARY) in m.encodings
    assert m.dictionary_page_offset is not None
    _pyarrow_equal(raw, t)
    _self_equal(raw, t)


def test_dictionary_fallback_high_cardinality(rng):
    t = pa.table({"s": pa.array([f"unique-value-{i}" for i in range(10000)])})
    raw = _write(t)
    pf = ParquetFile(raw)
    m = pf.metadata.row_groups[0].columns[0].meta_data
    assert int(Encoding.RLE_DICTIONARY) not in m.encodings  # fell back to plain
    _pyarrow_equal(raw, t)


def test_lists(rng):
    t = pa.table({
        "lst": pa.array([[1, 2, 3] if i % 2 else None for i in range(2000)],
                        type=pa.list_(pa.int64())),
        "empties": pa.array([[] if i % 5 == 0 else [None, i] for i in range(2000)],
                            type=pa.list_(pa.int32())),
        "strs": pa.array([[f"x{i}", None] if i % 3 else [] for i in range(2000)],
                         type=pa.list_(pa.string())),
    })
    raw = _write(t)
    _pyarrow_equal(raw, t)
    _self_equal(raw, t)


def test_nested_lists_deep(rng):
    """Multi-level lists through the columnar write path (levels_for_nested)."""
    def inner(i, j):
        return [int(v) for v in range(j % 4)] if (i + j) % 7 else None

    rows2 = [None if i % 11 == 3 else [inner(i, j) for j in range(i % 4)]
             for i in range(3000)]
    rows3 = [[[[f"d{i}-{k}"] * (k % 3) if k % 5 else None for k in range(j % 3)]
              for j in range(i % 3)] if i % 9 else ([] if i % 2 else None)
             for i in range(3000)]
    t = pa.table({
        "ll": pa.array(rows2, type=pa.list_(pa.list_(pa.int64()))),
        "lll": pa.array(rows3, type=pa.list_(pa.list_(pa.list_(pa.string())))),
    })
    raw = _write(t)
    _pyarrow_equal(raw, t)
    # multiple row groups + small pages stress the slicing path too
    raw = _write(t, row_group_size=700, data_page_size=2048, dictionary=False)
    _pyarrow_equal(raw, t)


def test_multiple_pages_and_row_groups(rng):
    t = pa.table({"x": pa.array(np.arange(100000, dtype=np.int64))})
    buf = io.BytesIO()
    schema = schema_from_arrow(t.schema)
    opts = WriterOptions(data_page_size=16 * 1024, dictionary=False)
    w = ParquetWriter(buf, schema, opts)
    for start in range(0, 100000, 30000):
        end = min(start + 30000, 100000)
        w.write_row_group(
            {"x": ColumnData(values=np.arange(start, end, dtype=np.int64))},
            end - start)
    w.close()
    raw = buf.getvalue()
    pf = ParquetFile(raw)
    assert len(pf.row_groups) == 4
    assert pf.num_rows == 100000
    _pyarrow_equal(raw, t)
    _self_equal(raw, t)


def test_statistics_and_column_index(rng):
    t = pa.table({"x": pa.array(np.arange(50000, dtype=np.int64))})
    raw = _write(t, data_page_size=32 * 1024, dictionary=False)
    pf = ParquetFile(raw)
    chunk = pf.row_group(0).column(0)
    st = chunk.statistics()
    assert st.min_value == 0 and st.max_value == 49999 and st.null_count == 0
    ci = chunk.column_index()
    oi = chunk.offset_index()
    assert ci is not None and oi is not None
    assert len(ci.min_values) == len(oi.page_locations) > 1
    # page mins must ascend for a sorted column
    from parquet_tpu.format.enums import BoundaryOrder
    assert ci.boundary_order == int(BoundaryOrder.ASCENDING)
    # pyarrow agrees with our statistics
    pam = pq.ParquetFile(io.BytesIO(raw)).metadata
    pst = pam.row_group(0).column(0).statistics
    assert pst.min == 0 and pst.max == 49999


def test_crc_written_and_verified(rng):
    t = pa.table({"x": pa.array(np.arange(1000, dtype=np.int64))})
    raw = _write(t, write_crc=True, dictionary=False)
    tab = ParquetFile(raw, ReadOptions(verify_crc=True)).read()
    np.testing.assert_array_equal(np.asarray(tab["x"].values), np.arange(1000))


def test_key_value_metadata_and_created_by(rng):
    t = pa.table({"x": pa.array([1, 2])})
    raw = _write(t, key_value_metadata={"origin": "unit-test"})
    pf = ParquetFile(raw)
    assert pf.key_value_metadata()["origin"] == "unit-test"
    assert "parquet-tpu" in pf.created_by


def test_sorting_columns_metadata(rng):
    t = pa.table({"x": pa.array(np.sort(rng.integers(0, 100, 100)))})
    raw = _write(t, sorting_columns=[("x", False, False)])
    pf = ParquetFile(raw)
    sc = pf.row_group(0).sorting_columns
    assert sc and sc[0].column_idx == 0 and not sc[0].descending


def test_bloom_filter_roundtrip(rng):
    vals = rng.integers(0, 10**12, 5000)
    t = pa.table({"x": pa.array(vals), "s": pa.array([f"k{i % 500}" for i in range(5000)])})
    raw = _write(t, bloom_filters={"x": 10, "s": 10}, dictionary=["s"])
    pf = ParquetFile(raw)
    bf = pf.row_group(0).column(0).bloom_filter()
    assert bf is not None
    leaf = pf.schema.leaves[0]
    # no false negatives
    for v in vals[:200]:
        assert bf.check(int(v), leaf)
    # bounded false positives
    probes = rng.integers(10**13, 10**14, 2000)
    fp = sum(bf.check(int(v), leaf) for v in probes)
    assert fp / len(probes) < 0.05
    # string bloom
    bfs = pf.row_group(0).column(1).bloom_filter()
    sleaf = pf.schema.leaves[1]
    assert bfs.check("k0", sleaf) and bfs.check("k499", sleaf)
    misses = sum(bfs.check(f"nope-{i}", sleaf) for i in range(500))
    assert misses / 500 < 0.05


def test_logical_types_roundtrip(rng):
    t = pa.table({
        "date": pa.array(np.arange(500, dtype=np.int32), type=pa.date32()),
        "ts": pa.array(rng.integers(0, 2**45, 500), type=pa.timestamp("us", tz="UTC")),
        "u16": pa.array(rng.integers(0, 65535, 500, dtype=np.uint16)),
        "dec": pa.array([__import__("decimal").Decimal(f"{i}.{i % 100:02d}")
                         for i in range(500)], type=pa.decimal128(18, 2)),
    })
    raw = _write(t)
    _pyarrow_equal(raw, t)


def test_empty_table():
    t = pa.table({"x": pa.array([], type=pa.int64())})
    raw = _write(t)
    got = pq.read_table(io.BytesIO(raw))
    assert got.num_rows == 0


def test_footer_last_atomicity(rng):
    """Truncated write (no footer) must be invalid — SURVEY.md §5."""
    t = pa.table({"x": pa.array(np.arange(100, dtype=np.int64))})
    raw = _write(t)
    with pytest.raises(Exception):
        ParquetFile(raw[: len(raw) - 20])


def test_limits_enforced():
    """errors.py limits are enforced, not just declared."""
    import pytest

    from parquet_tpu.errors import ColumnTooDeepError, MAX_COLUMN_DEPTH
    from parquet_tpu.format.enums import Type
    from parquet_tpu.schema import schema as sch

    # column depth: nest groups past the limit
    node = sch.leaf("x", Type.INT32)
    for i in range(MAX_COLUMN_DEPTH + 1):
        node = sch.group(f"g{i}", [node])
    with pytest.raises(ColumnTooDeepError, match="levels deep"):
        sch.message("root", [node])

    # a schema at exactly the limit is fine
    node = sch.leaf("x", Type.INT32)
    for i in range(MAX_COLUMN_DEPTH - 1):
        node = sch.group(f"g{i}", [node])
    assert len(sch.message("root", [node]).leaves[0].path) == MAX_COLUMN_DEPTH


def test_corrupted_page_size_rejected():
    """The MAX_PAGE_SIZE guard rejects absurd compressed-size claims."""
    from parquet_tpu.errors import CorruptedError
    from parquet_tpu.format import metadata as md, thrift
    from parquet_tpu.io.reader import ParquetFile

    t = pa.table({"x": pa.array(np.arange(100, dtype=np.int64))})
    buf = io.BytesIO()
    pq.write_table(t, buf, use_dictionary=False, compression="none")
    pf = ParquetFile(buf.getvalue())
    chunk = pf.row_group(0).column(0)
    # craft a page stream whose header claims a negative compressed size
    bad_header = md.PageHeader(
        type=int(Encoding.PLAIN) * 0,  # DATA_PAGE
        uncompressed_page_size=800, compressed_page_size=-7,
        data_page_header=md.DataPageHeader(
            num_values=100, encoding=0,
            definition_level_encoding=3, repetition_level_encoding=3))
    raw = thrift.serialize(bad_header) + b"\x00" * 16
    with pytest.raises(CorruptedError, match="out of range"):
        list(chunk.pages(raw=raw))


def test_corrupted_column_index_length_rejected():
    from parquet_tpu.errors import CorruptedError
    from parquet_tpu.io.reader import ParquetFile

    t = pa.table({"x": pa.array(np.arange(1000, dtype=np.int64))})
    buf = io.BytesIO()
    pq.write_table(t, buf, use_dictionary=False, write_page_index=True)
    pf = ParquetFile(buf.getvalue())
    chunk = pf.row_group(0).column(0)
    chunk.chunk.column_index_length = -5  # corrupt footer claim
    with pytest.raises(CorruptedError, match="out of range"):
        chunk.column_index()


def test_write_table_struct_and_map_from_arrow():
    """write_table must descend struct fields and map key/values when
    ingesting arrow arrays (r2: previously crashed in _build_dictionary)."""
    inner = pa.struct([("p", pa.int64()), ("q", pa.string())])
    outer = pa.struct([("i", inner), ("z", pa.int64())])
    rows = [{"i": {"p": 1, "q": "a"}, "z": 10},
            {"i": None, "z": 30},
            {"i": {"p": 4, "q": None}, "z": 40}]
    t = pa.table({"o": pa.array(rows, type=outer)})
    buf = io.BytesIO()
    write_table(t, buf, WriterOptions())
    back = pq.read_table(io.BytesIO(buf.getvalue()))
    got = back.column("o").to_pylist()
    assert got[0] == rows[0]
    assert got[1]["z"] == 30 and got[1]["i"] in (None, {"p": None, "q": None})

    m = pa.table({"m": pa.array([[("a", 1)], [("b", 2)], None, []],
                                type=pa.map_(pa.string(), pa.int64()))})
    buf = io.BytesIO()
    write_table(m, buf, WriterOptions())
    assert pq.read_table(io.BytesIO(buf.getvalue())).column("m").to_pylist() \
        == [[("a", 1)], [("b", 2)], None, []]

    ls = pa.table({"ls": pa.array([[{"a": 1}, {"a": None}], None, [], [{"a": 4}]],
                                  type=pa.list_(pa.struct([("a", pa.int64())])))})
    buf = io.BytesIO()
    write_table(ls, buf, WriterOptions())
    assert pq.read_table(io.BytesIO(buf.getvalue())).column("ls").to_pylist() \
        == [[{"a": 1}, {"a": None}], None, [], [{"a": 4}]]

    sl = pa.table({"sl": pa.array([{"xs": [1, 2]}, {"xs": None}],
                                  type=pa.struct([("xs", pa.list_(pa.int64()))]))})
    buf = io.BytesIO()
    write_table(sl, buf, WriterOptions())
    assert pq.read_table(io.BytesIO(buf.getvalue())).column("sl").to_pylist() \
        == [{"xs": [1, 2]}, {"xs": None}]


def test_write_table_struct_null_fidelity():
    """None-struct vs struct-of-None must round-trip exactly for flat struct
    chains (exact def levels from _struct_def_levels)."""
    inner = pa.struct([("p", pa.int64()), ("q", pa.string())])
    outer = pa.struct([("i", inner), ("z", pa.int64())])
    rows = [{"i": {"p": 1, "q": "a"}, "z": 10}, None, {"i": None, "z": 30},
            {"i": {"p": 4, "q": None}, "z": 40}]
    t = pa.table({"o": pa.array(rows, type=outer)})
    buf = io.BytesIO()
    write_table(t, buf, WriterOptions())
    assert pq.read_table(io.BytesIO(buf.getvalue())).column("o").to_pylist() == rows
    from parquet_tpu.io.reader import ParquetFile as PF
    assert PF(buf.getvalue()).read().to_arrow().column("o").to_pylist() == rows
    # struct nulls mixed with repetition raise loudly instead of corrupting
    t2 = pa.table({"sl": pa.array([{"xs": [1]}, None],
                                  type=pa.struct([("xs", pa.list_(pa.int64()))]))})
    with pytest.raises(NotImplementedError):
        write_table(t2, io.BytesIO(), WriterOptions())


def test_buffered_write_splits_row_groups(rng):
    """One oversized ParquetWriter.write() call still splits at
    row_group_size (MaxRowsPerRowGroup), incl. nulls and byte arrays."""
    from parquet_tpu.io.writer import ColumnData, ParquetWriter, WriterOptions
    from parquet_tpu.io.writer import schema_from_arrow

    n = 25000
    t = pa.table({
        "x": pa.array([None if i % 9 == 0 else i for i in range(n)],
                      type=pa.int64()),
        "s": pa.array([f"v{i % 13}" for i in range(n)]),
    })
    from parquet_tpu.io.writer import columns_from_arrow

    schema = schema_from_arrow(t.schema)
    buf = io.BytesIO()
    w = ParquetWriter(buf, schema, WriterOptions(row_group_size=6000,
                                                 compression="none"))
    w.write(columns_from_arrow(t, schema), n)
    w.close()
    pf = ParquetFile(buf.getvalue())
    assert [rg.num_rows for rg in pf.row_groups] == [6000, 6000, 6000, 6000, 1000]
    _pyarrow_equal(buf.getvalue(), t)


def test_writer_options_validated():
    import pytest as _pytest

    from parquet_tpu.io.writer import WriterOptions

    for kw in ({"row_group_size": 0}, {"data_page_size": 0},
               {"data_page_version": 3}):
        with _pytest.raises(ValueError):
            WriterOptions(**kw)


def test_streaming_writes_keep_tail_buffered(rng):
    """write() calls crossing the row-group boundary must not fragment the
    file: full groups are emitted, the tail stays buffered until close."""
    from parquet_tpu.io.writer import (ParquetWriter, WriterOptions,
                                       columns_from_arrow, schema_from_arrow)

    t = pa.table({"x": pa.array(list(range(7000)), type=pa.int64())})
    schema = schema_from_arrow(t.schema)
    buf = io.BytesIO()
    w = ParquetWriter(buf, schema, WriterOptions(row_group_size=6000,
                                                 compression="none"))
    for _ in range(2):
        w.write(columns_from_arrow(t, schema), 7000)
    w.close()
    pf = ParquetFile(buf.getvalue())
    assert [rg.num_rows for rg in pf.row_groups] == [6000, 6000, 2000]
    assert pf.read()["x"].to_arrow().to_pylist() == list(range(7000)) * 2


def test_column_index_truncation_long_strings(rng):
    """Page-index min/max for long byte arrays truncate to the configured
    limit (min = prefix, max = incremented prefix) and pushdown stays
    correct+conservative (ColumnIndexSizeLimit parity)."""
    import parquet_tpu as ptq
    from parquet_tpu.io.search import pages_overlapping

    long = ["p" * 200 + f"{i:04d}" for i in range(100)]
    t = pa.table({"s": pa.array(sorted(long))})
    buf = io.BytesIO()
    ptq.write_table(t, buf, ptq.WriterOptions(
        compression="none", data_page_size=1 << 10))
    pf = ptq.ParquetFile(buf.getvalue())
    chunk = pf.row_group(0).column("s")
    ci = chunk.column_index()
    assert ci is not None and len(ci.min_values) > 1
    assert all(len(m) <= 64 for m in ci.min_values)
    assert all(len(m) <= 65 for m in ci.max_values)
    # truncated bounds bracket each page's true min/max (bytewise order)
    vals = sorted(long)
    row = 0
    for pg, (mn, mx) in enumerate(zip(ci.min_values, ci.max_values)):
        locs = chunk.offset_index().page_locations
        n_rows = ((locs[pg + 1].first_row_index if pg + 1 < len(locs)
                   else len(vals)) - locs[pg].first_row_index)
        page_vals = [v.encode() for v in vals[row: row + n_rows]]
        row += n_rows
        assert mn <= min(page_vals) and mx >= max(page_vals), pg
    target = "p" * 200 + "0050"
    pages = pages_overlapping(ci, chunk.leaf, target, target)
    rows = pf.read().to_arrow().column("s").to_pylist()
    assert target in rows
    assert len(pages) >= 1  # the page holding the value always survives

    # all-0xFF max cannot be incremented: full value is kept
    t2 = pa.table({"b": pa.array([b"\xff" * 100, b"\x01"])})
    b2 = io.BytesIO()
    ptq.write_table(t2, b2, ptq.WriterOptions(compression="none"))
    ci2 = ptq.ParquetFile(b2.getvalue()).row_group(0).column("b").column_index()
    assert max(len(m) for m in ci2.max_values) == 100


def test_null_type_column_roundtrip(rng):
    """Arrow's untyped all-null columns map to the parquet Null logical type
    over optional INT32 (pyarrow's mapping) and round-trip both directions."""
    import parquet_tpu as ptq

    t = pa.table({"n": pa.array([None] * 500), "x": pa.array(np.arange(500))})
    buf = io.BytesIO()
    ptq.write_table(t, buf, ptq.WriterOptions(compression="none"))
    raw = buf.getvalue()
    got = pq.read_table(io.BytesIO(raw))
    assert got.column("n").null_count == 500
    assert got.column("x").to_pylist() == list(range(500))
    back = ptq.ParquetFile(raw).read().to_arrow()
    assert back.column("n").type == pa.null() and back.column("n").null_count == 500
    # pyarrow-written null column reads back as null type too
    b2 = io.BytesIO()
    pq.write_table(t, b2)
    back2 = ptq.ParquetFile(b2.getvalue()).read().to_arrow()
    assert back2.column("n").type == pa.null() and back2.column("n").null_count == 500


def test_sticky_dict_fallback_ignores_empty_chunks():
    """An all-null first row group must not sticky-disable dictionary
    encoding for later row groups of the column."""
    n = 6000
    s = np.array([None] * (n // 2) + [f"v{i % 5}" for i in range(n // 2)],
                 dtype=object)
    t = pa.table({"s": pa.array(s)})
    buf = io.BytesIO()
    write_table(t, buf, WriterOptions(compression="snappy",
                                      row_group_size=n // 2))
    meta = pq.ParquetFile(io.BytesIO(buf.getvalue())).metadata
    encs = [str(e) for e in meta.row_group(1).column(0).encodings]
    assert any("DICTIONARY" in e for e in encs), encs
    back = pq.read_table(io.BytesIO(buf.getvalue()))
    assert back.column("s").to_pylist() == t.column("s").to_pylist()
