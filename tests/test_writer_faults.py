"""Crash-safe write suite (ISSUE 2): atomic commit, abort-on-exception,
write-side fault injection, the crash-consistency matrix, and end-to-end
``verify_file`` integrity checks.

The invariant under test is the write-side mirror of the chaos suite's
(test_faults.py) read-side guarantees: whatever fault interrupts a write —
transient I/O error, short write, full disk, or a hard crash at an arbitrary
byte — the destination path afterwards either does not exist or holds a
complete file that verifies clean."""

import dataclasses
import errno
import io
import os

import numpy as np
import pyarrow as pa
import pytest

from parquet_tpu import (AtomicFileSink, FaultInjectingSink, FileSink,
                         InjectedWriterCrash, ParquetFile, ParquetWriter,
                         TypedWriter, WriteError, WriterOptions,
                         crash_consistency_check, schema_from_arrow,
                         verify_file, write_table)
from parquet_tpu.io.writer import columns_from_arrow

N_ROWS = 6000
RG = 2000  # 3 row groups


def _make_table() -> "pa.Table":
    return pa.table({
        "x": pa.array(np.arange(N_ROWS, dtype=np.int64)),
        "s": pa.array([f"v{i % 23}" for i in range(N_ROWS)]),
    })


@pytest.fixture(scope="module")
def table():
    return _make_table()


@pytest.fixture(scope="module")
def schema(table):
    return schema_from_arrow(table.schema)


def _no_temps(d) -> bool:
    return not [f for f in os.listdir(d) if f.endswith(".tmp")]


# ---------------------------------------------------------------------------
# atomic commit on the happy path
# ---------------------------------------------------------------------------
def test_atomic_write_round_trips_and_verifies(tmp_path, table):
    dest = tmp_path / "a.parquet"
    write_table(table, str(dest), WriterOptions(row_group_size=RG))
    assert _no_temps(tmp_path)
    assert ParquetFile(str(dest)).read().to_arrow().equals(table)
    rep = verify_file(str(dest))
    assert rep.ok, rep.summary()
    assert rep.crcs_checked > 0  # write_crc now defaults on


def test_pathlike_sink_supported(tmp_path, table):
    dest = tmp_path / "p.parquet"  # a PathLike, not a str
    write_table(table, dest)
    assert verify_file(dest).ok


def test_verify_file_leaves_caller_file_object_open(tmp_path, table):
    dest = tmp_path / "v.parquet"
    write_table(table, str(dest))
    with open(dest, "rb") as f:
        assert verify_file(f).ok
        f.seek(0)
        assert f.read(4) == b"PAR1"  # the caller's handle survives verify


def test_atomic_commit_opt_out_still_cleans_on_abort(tmp_path, table, schema):
    dest = tmp_path / "direct.parquet"
    opts = WriterOptions(atomic_commit=False, row_group_size=RG)
    with pytest.raises(RuntimeError):
        with ParquetWriter(str(dest), schema, opts) as w:
            w.write_row_group(columns_from_arrow(table, schema), N_ROWS)
            raise RuntimeError("boom")
    # non-atomic: bytes were going straight to dest — abort must unlink it
    assert not dest.exists()


# ---------------------------------------------------------------------------
# satellite: __exit__ aborts, close is failure-safe, __init__ leaks nothing
# ---------------------------------------------------------------------------
def test_exit_aborts_on_exception_no_destination(tmp_path, table, schema):
    dest = tmp_path / "b.parquet"
    with pytest.raises(RuntimeError):
        with ParquetWriter(str(dest), schema) as w:
            w.write_row_group(columns_from_arrow(table, schema), N_ROWS)
            raise RuntimeError("mid-write failure")
    assert not dest.exists()
    assert _no_temps(tmp_path)


def test_abort_is_idempotent_and_blocks_close(tmp_path, schema):
    w = ParquetWriter(str(tmp_path / "c.parquet"), schema)
    w.abort()
    w.abort()  # idempotent
    with pytest.raises(ValueError, match="aborted"):
        w.close()
    assert _no_temps(tmp_path)


def test_write_after_close_raises(tmp_path, table, schema):
    dest = tmp_path / "d.parquet"
    w = ParquetWriter(str(dest), schema)
    cols = columns_from_arrow(table, schema)
    w.write_row_group(cols, N_ROWS)
    w.close()
    with pytest.raises(ValueError, match="closed"):
        w.write_row_group(cols, N_ROWS)
    w.close()  # close-after-close stays a no-op
    assert verify_file(str(dest)).ok


def test_magic_write_failure_does_not_leak_temp(tmp_path, schema, monkeypatch):
    def boom(self, data):
        raise OSError(errno.EIO, "disk gone at open")

    monkeypatch.setattr(AtomicFileSink, "write", boom)
    with pytest.raises(OSError):
        ParquetWriter(str(tmp_path / "e.parquet"), schema)
    assert os.listdir(tmp_path) == []  # no temp file, no destination


def test_close_commit_failure_aborts_and_raises_write_error(
        tmp_path, table, schema, monkeypatch):
    dest = tmp_path / "f.parquet"
    w = ParquetWriter(str(dest), schema)
    w.write_row_group(columns_from_arrow(table, schema), N_ROWS)

    def no_replace(src, dst):
        raise OSError(errno.EACCES, "rename denied")

    monkeypatch.setattr(os, "replace", no_replace)
    with pytest.raises(WriteError) as ei:
        w.close()
    assert ei.value.path == str(dest)  # located failure
    assert not w._closed  # a failed close must not claim success
    monkeypatch.undo()
    assert not dest.exists()
    assert _no_temps(tmp_path)
    with pytest.raises(ValueError, match="aborted"):
        w.close()


def test_partial_footer_write_leaves_no_committed_file(tmp_path, table,
                                                       schema):
    # probe: how many bytes does the full write take?
    probe = FaultInjectingSink(io.BytesIO())
    with ParquetWriter(probe, schema) as w:
        w.write_row_group(columns_from_arrow(table, schema), N_ROWS)
    total = probe.stats.bytes_written
    # replay with the disk filling up 30 bytes before the end: the footer
    # write fails, the commit must never run
    dest = tmp_path / "g.parquet"
    sink = FaultInjectingSink(AtomicFileSink(str(dest)),
                              enospc_at_byte=total - 30)
    w = ParquetWriter(sink, schema)
    w.write_row_group(columns_from_arrow(table, schema), N_ROWS)
    with pytest.raises(OSError):
        w.close()
    assert not w._closed
    sink.abort()  # the caller owns a non-path sink's cleanup
    assert not dest.exists()
    assert _no_temps(tmp_path)


def test_typed_writer_exit_aborts(tmp_path):
    @dataclasses.dataclass
    class Rec:
        x: int

    dest = tmp_path / "typed.parquet"
    with pytest.raises(RuntimeError):
        with TypedWriter(str(dest), Rec) as tw:
            tw.write([Rec(x=i) for i in range(100)])
            raise RuntimeError("boom")
    assert not dest.exists()
    assert _no_temps(tmp_path)


# ---------------------------------------------------------------------------
# write-side fault injection
# ---------------------------------------------------------------------------
def test_enospc_mid_row_group(tmp_path, table, schema):
    dest = tmp_path / "enospc.parquet"
    sink = FaultInjectingSink(AtomicFileSink(str(dest)), enospc_at_byte=4096)
    with pytest.raises(OSError) as ei:
        with ParquetWriter(sink, schema,
                           WriterOptions(row_group_size=RG)) as w:
            w.write_row_group(columns_from_arrow(table, schema), N_ROWS)
    assert ei.value.errno == errno.ENOSPC
    assert sink.stats.bytes_written <= 4096  # nothing persisted past the cap
    sink.abort()
    assert not dest.exists()
    assert _no_temps(tmp_path)


def test_short_write_injection_surfaces(table, schema):
    sink = FaultInjectingSink(io.BytesIO(), seed=3, short_write_rate=1.0)
    with pytest.raises(OSError, match="short write"):
        with ParquetWriter(sink, schema) as w:
            w.write_row_group(columns_from_arrow(table, schema), N_ROWS)
    assert sink.stats.injected_short_writes == 1


def test_injection_is_deterministic(table, schema):
    def run(seed):
        sink = FaultInjectingSink(io.BytesIO(), seed=seed, error_rate=0.3)
        try:
            with ParquetWriter(sink, schema) as w:
                w.write_row_group(columns_from_arrow(table, schema), N_ROWS)
        except OSError:
            pass
        return (sink.stats.writes, sink.stats.bytes_written,
                sink.stats.injected_errors)

    assert run(11) == run(11)
    assert run(11) != run(12)  # different seed, different fault schedule


def test_crash_sink_kills_flush_and_commit():
    sink = FaultInjectingSink(io.BytesIO(), crash_at_byte=2)
    with pytest.raises(InjectedWriterCrash):
        sink.write(b"PAR1")
    assert sink.stats.crashed
    with pytest.raises(InjectedWriterCrash):
        sink.write(b"x")
    with pytest.raises(InjectedWriterCrash):
        sink.flush()
    with pytest.raises(InjectedWriterCrash):
        sink.close()


def test_crash_leaves_temp_stranded_but_dest_absent(tmp_path, table, schema):
    dest = tmp_path / "crash.parquet"
    sink = FaultInjectingSink(AtomicFileSink(str(dest)), crash_at_byte=1000)
    with pytest.raises(InjectedWriterCrash):
        w = ParquetWriter(sink, schema)
        w.write_row_group(columns_from_arrow(table, schema), N_ROWS)
    # a dead process leaves its temp file; the destination is untouched
    assert not dest.exists()
    assert sink.inner.temp_path is not None
    assert os.path.exists(sink.inner.temp_path)
    sink.abort()  # the restarted process's *.tmp sweep
    assert _no_temps(tmp_path)


# ---------------------------------------------------------------------------
# acceptance: crash-consistency matrix
# ---------------------------------------------------------------------------
def test_crash_consistency_matrix(tmp_path, table):
    dest = str(tmp_path / "matrix.parquet")
    opts = WriterOptions(row_group_size=RG, bloom_filters={"s": 10})
    results = crash_consistency_check(
        lambda sink: write_table(table, sink, opts), dest,
        samples=10, seed=42)
    # every sampled crash offset left the destination absent (atomic rename
    # means a clean-but-partial dest is impossible); the uncrashed control
    # run committed and verified clean
    assert [r["outcome"] for r in results[:-1]] == ["absent"] * (
        len(results) - 1)
    assert results[-1] == {"offset": None, "outcome": "clean"}
    assert _no_temps(tmp_path)
    rep = verify_file(dest, decode=True)
    assert rep.ok and rep.chunks_decoded == 6, rep.summary()


# ---------------------------------------------------------------------------
# acceptance: verify_file flags every injectable corruption class
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def clean_bytes(table):
    buf = io.BytesIO()
    write_table(table, buf, WriterOptions(row_group_size=RG,
                                          bloom_filters={"s": 10}))
    return buf.getvalue()


def _payload_offset(raw: bytes) -> int:
    cm = ParquetFile(raw).metadata.row_groups[0].columns[0].meta_data
    return cm.data_page_offset + cm.total_compressed_size // 2


def test_verify_clean_file(clean_bytes):
    rep = verify_file(clean_bytes)
    assert rep.ok, rep.summary()
    assert rep.pages_checked > 0 and rep.crcs_checked > 0
    d = rep.as_dict()
    assert d["ok"] is True and d["issues"] == []


def test_verify_flags_crcd_bit_flip(clean_bytes):
    b = bytearray(clean_bytes)
    b[_payload_offset(clean_bytes)] ^= 0x01  # single-bit rot in page body
    rep = verify_file(bytes(b))
    assert not rep.ok
    assert any(i.kind == "crc" for i in rep.issues), rep.summary()
    issue = next(i for i in rep.issues if i.kind == "crc")
    assert issue.row_group == 0 and issue.column == "x"  # located


def test_verify_flags_truncation(clean_bytes):
    rep = verify_file(clean_bytes[:-500])
    assert not rep.ok
    assert rep.issues[0].kind in ("magic", "footer"), rep.summary()


def test_verify_flags_bad_footer_length(clean_bytes):
    b = bytearray(clean_bytes)
    b[-8:-4] = (len(b) * 2).to_bytes(4, "little")
    rep = verify_file(bytes(b))
    assert not rep.ok and rep.issues[0].kind == "footer", rep.summary()


def test_verify_flags_smashed_page_header(clean_bytes):
    cm = ParquetFile(clean_bytes).metadata.row_groups[1].columns[0].meta_data
    off = cm.dictionary_page_offset or cm.data_page_offset
    b = bytearray(clean_bytes)
    b[off : off + 4] = b"\xff\xff\xff\xff"
    rep = verify_file(bytes(b))
    assert not rep.ok
    assert any(i.kind in ("page", "metadata") and i.row_group == 1
               for i in rep.issues), rep.summary()


def test_verify_decode_mode_counts_chunks(clean_bytes):
    rep = verify_file(clean_bytes, decode=True)
    assert rep.ok and rep.chunks_decoded == 6, rep.summary()


def test_verify_report_is_machine_readable(clean_bytes):
    b = bytearray(clean_bytes)
    b[_payload_offset(clean_bytes)] ^= 0xFF
    d = verify_file(bytes(b)).as_dict()
    assert set(d) >= {"path", "ok", "file_size", "row_groups",
                      "pages_checked", "crcs_checked", "issues"}
    issue = d["issues"][0]
    assert set(issue) == {"kind", "message", "row_group", "column", "offset"}


def test_verify_pyarrow_written_file(table):
    import pyarrow.parquet as pq

    buf = io.BytesIO()
    pq.write_table(table, buf, row_group_size=RG)
    rep = verify_file(buf.getvalue())
    assert rep.ok, rep.summary()


# ---------------------------------------------------------------------------
# review regressions: buffered-write guards and front-end abort
# ---------------------------------------------------------------------------
def test_buffered_write_and_flush_after_close_raise(tmp_path, table, schema):
    dest = tmp_path / "h.parquet"
    w = ParquetWriter(str(dest), schema)
    w.write(columns_from_arrow(table, schema), N_ROWS)
    w.close()
    # write() buffers; without the guard these rows would vanish silently
    with pytest.raises(ValueError, match="closed"):
        w.write(columns_from_arrow(table, schema), N_ROWS)
    with pytest.raises(ValueError, match="closed"):
        w.flush()
    w2 = ParquetWriter(str(tmp_path / "i.parquet"), schema)
    w2.abort()
    with pytest.raises(ValueError, match="aborted"):
        w2.write(columns_from_arrow(table, schema), N_ROWS)


def test_write_table_failure_aborts_path_sink(tmp_path, table):
    from parquet_tpu.schema import schema as sch
    from parquet_tpu.format.enums import FieldRepetitionType as Rep, Type
    from parquet_tpu.schema.schema import Schema

    dest = tmp_path / "j.parquet"
    # schema names a column the table lacks: write_table fails mid-loop
    bogus = Schema(sch.Node(name="schema", children=[
        sch.leaf("missing", Type.INT64, Rep.OPTIONAL)]))
    with pytest.raises(KeyError):
        write_table(table, str(dest), schema=bogus)
    assert not dest.exists()
    assert _no_temps(tmp_path)  # the temp file was swept by abort()


def test_commit_failure_releases_fd(tmp_path, monkeypatch):
    import gc

    def no_fsync(fd):
        raise OSError(errno.EIO, "fsync failed")

    fd_dir = "/proc/self/fd"
    gc.collect()
    before = len(os.listdir(fd_dir))
    for i in range(20):
        sink = AtomicFileSink(str(tmp_path / f"fd{i}.parquet"))
        sink.write(b"PAR1")
        monkeypatch.setattr(os, "fsync", no_fsync)
        with pytest.raises(WriteError):
            sink.close()
        monkeypatch.undo()
    assert len(os.listdir(fd_dir)) <= before + 1  # no fd accumulation
    assert _no_temps(tmp_path)


def test_intentional_abort_inside_cm_exits_cleanly(tmp_path, table, schema):
    dest = tmp_path / "k.parquet"
    with ParquetWriter(str(dest), schema) as w:
        w.write(columns_from_arrow(table, schema), N_ROWS)
        w.abort()  # caller decides to discard — must not turn into an error
    assert not dest.exists()
    assert _no_temps(tmp_path)
    with TypedWriter(str(tmp_path / "l.parquet"), _Rec) as tw:
        tw.write([_Rec(x=1)])
        tw.abort()
    assert _no_temps(tmp_path)


@dataclasses.dataclass
class _Rec:
    x: int


def test_typed_writer_close_drain_failure_aborts(tmp_path, monkeypatch):
    dest = tmp_path / "m.parquet"
    tw = TypedWriter(str(dest), _Rec)
    tw.write([_Rec(x=i) for i in range(10)])  # stays pending

    def boom(self, columns, num_rows):
        raise OSError(errno.ENOSPC, "disk full during close-time drain")

    monkeypatch.setattr(ParquetWriter, "write_row_group", boom)
    with pytest.raises(OSError):
        tw.close()
    assert not dest.exists()
    assert _no_temps(tmp_path)  # the drain failed before writer.close()


def test_abort_unlink_failure_does_not_mask_original(tmp_path, table, schema,
                                                     monkeypatch):
    dest = tmp_path / "n.parquet"

    def no_unlink(p):
        raise OSError(errno.EACCES, "stale NFS handle")

    with pytest.raises(RuntimeError, match="original"):
        with ParquetWriter(str(dest), schema) as w:
            w.write(columns_from_arrow(table, schema), N_ROWS)
            monkeypatch.setattr(os, "unlink", no_unlink)
            raise RuntimeError("original failure")
    monkeypatch.undo()


def test_sorting_spills_skip_atomic_commit(tmp_path, table):
    from parquet_tpu import SortingColumn, SortingWriter

    dest = tmp_path / "sorted.parquet"
    with SortingWriter(str(dest), schema_from_arrow(table.schema),
                       [SortingColumn("x", descending=True)],
                       buffer_rows=1500) as sw:
        sw.write_arrow(table)  # > buffer_rows: forces spills
    # final output still verifies; spills never leaked temps anywhere
    assert verify_file(str(dest)).ok
    assert _no_temps(tmp_path)
    got = np.asarray(ParquetFile(str(dest)).read()["x"].values)
    assert (got == np.arange(N_ROWS, dtype=np.int64)[::-1]).all()
