"""Pipelined write path (ISSUE 4): double-buffered encode/emit overlap,
BufferedSink writeback coalescing, WriteStats observability, and the
overlap x writer-fault matrix.

The invariants under test mirror the read pipeline's (test_prefetch.py):
every pipeline configuration — overlap off/forced, writeback buffer off/on,
pool width 1/N — must produce byte-identical files, and every injected
write fault (ENOSPC, short write, hard crash) under overlap must leave the
destination either absent or verifying clean, never torn."""

import dataclasses
import errno
import io
import os
import time

import numpy as np
import pyarrow as pa
import pytest

from parquet_tpu import (AtomicFileSink, BufferedSink, FaultInjectingSink,
                         InjectedWriterCrash, ParquetFile, ParquetWriter,
                         SortingColumn, SortingWriter, TypedWriter,
                         WriteStats, WriterOptions, crash_consistency_check,
                         schema_from_arrow, verify_file, write_table)
from parquet_tpu.io.writer import columns_from_arrow
from parquet_tpu.rows import write_rows
from parquet_tpu.utils import pool as pool_mod

N_ROWS = 12000
RG = 2000  # 6 row groups


def _mixed_table(n=N_ROWS) -> "pa.Table":
    rng = np.random.default_rng(5)
    lens = rng.integers(0, 4, n)
    offs = np.zeros(n + 1, np.int32)
    np.cumsum(lens, out=offs[1:])
    x = np.arange(n, dtype=np.int64)
    return pa.table({
        "x": pa.array(x),
        "f": pa.array(rng.random(n)),
        "s": pa.array([f"v{i % 37}" for i in range(n)]),
        "ox": pa.array(np.where(x % 5 == 0, None, x), type=pa.int64()),
        "lst": pa.ListArray.from_arrays(
            pa.array(offs), pa.array(np.arange(offs[-1], dtype=np.int64))),
    })


@pytest.fixture(scope="module")
def table():
    return _mixed_table()


@pytest.fixture(scope="module")
def schema(table):
    return schema_from_arrow(table.schema)


def _no_temps(d) -> bool:
    return not [f for f in os.listdir(d) if f.endswith(".tmp")]


def _write_bytes(table, opts, monkeypatch=None, overlap="0", buffer="0",
                 via_write=False):
    if monkeypatch is not None:
        monkeypatch.setenv("PARQUET_TPU_WRITE_OVERLAP", overlap)
        monkeypatch.setenv("PARQUET_TPU_WRITE_BUFFER", buffer)
    buf = io.BytesIO()
    schema = schema_from_arrow(table.schema)
    w = ParquetWriter(buf, schema, opts)
    if via_write:
        # the write()-buffered front end: slabs that straddle group bounds
        step = RG // 3 + 17
        for start in range(0, table.num_rows, step):
            part = table.slice(start, min(step, table.num_rows - start))
            w.write(columns_from_arrow(part, schema), part.num_rows)
    else:
        for start in range(0, table.num_rows, RG):
            part = table.slice(start, RG)
            w.write_row_group(columns_from_arrow(part, schema),
                              part.num_rows)
    w.close()
    return buf.getvalue(), w.write_stats


# ---------------------------------------------------------------------------
# equivalence: every pipeline configuration produces identical bytes
# ---------------------------------------------------------------------------
def test_overlap_on_off_byte_identical(table, monkeypatch):
    opts = WriterOptions(row_group_size=RG)
    base, st0 = _write_bytes(table, opts, monkeypatch, overlap="0")
    forced, st1 = _write_bytes(table, opts, monkeypatch, overlap="force")
    assert forced == base
    assert st0.overlapped_groups == 0
    assert st1.overlapped_groups == st1.row_groups == 6
    assert ParquetFile(forced).read().to_arrow().equals(table)


def test_overlap_equivalence_via_buffered_write_path(table, monkeypatch):
    # write() accumulation (slab sizes straddling group boundaries) drains
    # through the same pipelined write_row_group
    opts = WriterOptions(row_group_size=RG)
    base, _ = _write_bytes(table, opts, monkeypatch, overlap="0",
                           via_write=True)
    forced, st = _write_bytes(table, opts, monkeypatch, overlap="force",
                              via_write=True)
    assert forced == base
    assert st.overlapped_groups > 0


def test_overlap_equivalence_with_dict_overflow(monkeypatch):
    # high-cardinality strings overflow the dictionary limit mid-file: the
    # sticky fallback must engage at the same group with overlap on or off
    # (encode N+1 only starts after encode N finished)
    n = 6000
    t = pa.table({"s": pa.array([f"unique-{i:08d}" for i in range(n)])})
    opts = WriterOptions(row_group_size=1000, dictionary_page_limit=4096)
    base, _ = _write_bytes(t, opts, monkeypatch, overlap="0")
    forced, _ = _write_bytes(t, opts, monkeypatch, overlap="force")
    assert forced == base


@pytest.mark.parametrize("width", ["1", "8"])
def test_overlap_pool_width_equivalence(table, monkeypatch, width):
    opts = WriterOptions(row_group_size=RG)
    base, _ = _write_bytes(table, opts, monkeypatch, overlap="0")
    monkeypatch.setenv("PARQUET_TPU_POOL_WORKERS", width)
    monkeypatch.setattr(pool_mod, "_POOL", None)  # rebuild at new width
    try:
        got, _ = _write_bytes(table, opts, monkeypatch, overlap="force")
    finally:
        monkeypatch.undo()
        pool_mod._POOL = None  # next user rebuilds at the ambient width
    assert got == base


def test_rows_path_overlap_equivalence(monkeypatch):
    from parquet_tpu import leaf, message
    from parquet_tpu.format.enums import FieldRepetitionType as Rep, Type

    schema = message("rec", [
        leaf("a", Type.INT64),
        leaf("b", Type.BYTE_ARRAY, Rep.OPTIONAL, logical="string")])
    records = [{"a": i, "b": None if i % 7 == 0 else f"r{i % 13}"}
               for i in range(5000)]
    opts = WriterOptions(row_group_size=800)

    def run(mode):
        monkeypatch.setenv("PARQUET_TPU_WRITE_OVERLAP", mode)
        buf = io.BytesIO()
        w = write_rows(buf, schema, records, opts)
        return buf.getvalue(), w.write_stats

    base, _ = run("0")
    forced, st = run("force")
    assert forced == base
    assert st.overlapped_groups > 0


# ---------------------------------------------------------------------------
# WriteStats observability
# ---------------------------------------------------------------------------
def test_write_stats_meters_the_pipeline(table, monkeypatch, tmp_path):
    monkeypatch.setenv("PARQUET_TPU_WRITE_OVERLAP", "force")
    monkeypatch.delenv("PARQUET_TPU_WRITE_BUFFER", raising=False)
    dest = tmp_path / "stats.parquet"
    w = write_table(table, str(dest), WriterOptions(row_group_size=RG))
    st = w.write_stats
    assert st.row_groups == 6 and st.overlapped_groups == 6
    assert st.encode_s > 0 and st.emit_s > 0
    # every byte that reached the OS is accounted, including magic + footer
    assert st.bytes_flushed == os.path.getsize(dest)
    assert 0.0 <= st.overlap_ratio() <= 1.0
    d = st.as_dict()
    assert set(d) == {"row_groups", "overlapped_groups", "encode_s",
                      "emit_s", "pool_wait_s", "overlap_ratio",
                      "bytes_buffered", "bytes_flushed", "sink_flushes",
                      "writev_flushes"}


def test_write_stats_serial_mode_zero_overlap(table, monkeypatch):
    base, st = _write_bytes(table, WriterOptions(row_group_size=RG),
                            monkeypatch, overlap="0")
    assert st.overlapped_groups == 0 and st.pool_wait_s == 0.0
    assert st.overlap_ratio() == 0.0
    assert st.encode_s > 0  # serial encodes are still metered


def test_typed_writer_surfaces_write_stats(tmp_path):
    @dataclasses.dataclass
    class Rec:
        x: int

    with TypedWriter(str(tmp_path / "t.parquet"), Rec) as tw:
        tw.write([Rec(x=i) for i in range(100)])
    assert isinstance(tw.write_stats, WriteStats)
    assert tw.write_stats.row_groups == 1


def test_sorting_writer_surfaces_write_stats(tmp_path, table):
    dest = tmp_path / "sorted.parquet"
    with SortingWriter(str(dest), schema_from_arrow(table.schema),
                       [SortingColumn("x", descending=True)],
                       buffer_rows=3000) as sw:
        sw.write_arrow(table)  # > buffer_rows: forces the spill-merge path
    assert verify_file(str(dest)).ok
    assert sw.write_stats is not None and sw.write_stats.row_groups > 0


# ---------------------------------------------------------------------------
# the overlap actually overlaps: a blocking (GIL-releasing) sink
# ---------------------------------------------------------------------------
class _ThrottledSink:
    """Simulated slow storage: writes block with the GIL released."""

    def __init__(self, rate_bps=50e6):
        self.buf = io.BytesIO()
        self.rate = rate_bps

    def write(self, d):
        time.sleep(len(d) / self.rate)
        return self.buf.write(d)

    def writelines(self, parts):
        for p in parts:
            self.write(p)

    def flush(self):
        pass

    def close(self):
        pass


def test_overlap_hides_encode_behind_blocking_sink(table, monkeypatch):
    opts = WriterOptions(row_group_size=RG)
    schema = schema_from_arrow(table.schema)

    def run(mode):
        monkeypatch.setenv("PARQUET_TPU_WRITE_OVERLAP", mode)
        sink = _ThrottledSink()
        w = ParquetWriter(sink, schema, opts)
        for start in range(0, table.num_rows, RG):
            part = table.slice(start, RG)
            w.write_row_group(columns_from_arrow(part, schema),
                              part.num_rows)
        w.close()
        return sink.buf.getvalue(), w.write_stats

    base, _ = run("0")
    forced, st = run("force")
    assert forced == base
    # while group N's pages sat in the sink's blocking writes, group N+1
    # encoded in the background: emit never (materially) waited on encode
    assert st.overlap_ratio() > 0.3, st.as_dict()


# ---------------------------------------------------------------------------
# BufferedSink unit behavior
# ---------------------------------------------------------------------------
class _CountingSink:
    def __init__(self):
        self.buf = io.BytesIO()
        self.write_calls = 0
        self.writelines_calls = 0
        self.closed = False
        self.aborted = False

    def write(self, d):
        self.write_calls += 1
        return self.buf.write(d)

    def writelines(self, parts):
        self.writelines_calls += 1
        for p in parts:
            self.buf.write(p)

    def flush(self):
        pass

    def close(self):
        self.closed = True

    def abort(self):
        self.aborted = True


def test_buffered_sink_coalesces_small_writes():
    inner = _CountingSink()
    st = WriteStats()
    b = BufferedSink(inner, buffer_bytes=1024, stats=st)
    for i in range(64):
        b.write(bytes([i]) * 100)  # 6400 bytes in 100-byte pages
    assert inner.write_calls == 0
    assert inner.writelines_calls == 5  # ~1.1 KB vectored flushes
    b.flush()
    assert inner.buf.getvalue() == b"".join(bytes([i]) * 100
                                            for i in range(64))
    assert st.bytes_buffered == 6400 and st.bytes_flushed == 6400
    assert st.sink_flushes == 6


def test_buffered_sink_close_drains_then_closes():
    inner = _CountingSink()
    b = BufferedSink(inner, buffer_bytes=1 << 20)
    b.write(b"tail bytes")
    b.close()
    assert inner.closed and inner.buf.getvalue() == b"tail bytes"


def test_buffered_sink_abort_drops_buffer():
    inner = _CountingSink()
    b = BufferedSink(inner, buffer_bytes=1 << 20)
    b.write(b"never flushed")
    b.abort()
    assert inner.aborted and inner.buf.getvalue() == b""


def test_buffered_sink_passthrough_mode_counts():
    inner = _CountingSink()
    st = WriteStats()
    b = BufferedSink(inner, buffer_bytes=0, stats=st)
    b.write(b"abc")
    b.writelines([b"de", b"f"])
    assert inner.buf.getvalue() == b"abcdef"
    assert st.bytes_flushed == 6 and st.bytes_buffered == 0


def test_writev_vectored_flush_on_path_sinks(table, monkeypatch, tmp_path):
    # raw-fd sinks (FileSink/AtomicFileSink under the writer's BufferedSink)
    # take the true os.writev path; bytes are identical to the
    # writelines-only pass-through
    opts = WriterOptions(row_group_size=RG)
    monkeypatch.setenv("PARQUET_TPU_WRITE_BUFFER", "0")
    p0 = tmp_path / "plain.parquet"
    write_table(table, str(p0), opts)
    monkeypatch.setenv("PARQUET_TPU_WRITE_BUFFER", str(1 << 16))
    p1 = tmp_path / "vectored.parquet"
    w1 = write_table(table, str(p1), opts)
    assert p0.read_bytes() == p1.read_bytes()
    if hasattr(os, "writev"):
        assert w1.write_stats.writev_flushes == w1.write_stats.sink_flushes
    assert w1.write_stats.bytes_flushed == os.path.getsize(p1)


def test_writev_falls_back_without_raw_fd():
    # a sink with no raw_fd (in-memory, injector wrappers) keeps the
    # writelines path and the same bytes
    inner = _CountingSink()
    st = WriteStats()
    b = BufferedSink(inner, buffer_bytes=256, stats=st)
    payload = [bytes([i]) * 100 for i in range(16)]
    for part in payload:
        b.write(part)
    b.close()
    assert inner.buf.getvalue() == b"".join(payload)
    assert st.writev_flushes == 0 and st.sink_flushes > 0


def test_writev_all_resumes_partial_and_batches_iov(monkeypatch, tmp_path):
    from parquet_tpu.io import sink as sink_mod

    if not hasattr(os, "writev"):
        pytest.skip("no os.writev on this platform")
    # IOV_MAX batching: more parts than the cap still all land, in order
    monkeypatch.setattr(sink_mod, "_IOV_MAX", 4)
    parts = [bytes([i]) * 13 for i in range(11)]
    p = tmp_path / "iov.bin"
    fd = os.open(str(p), os.O_WRONLY | os.O_CREAT)
    try:
        sink_mod._writev_all(fd, parts)
    finally:
        os.close(fd)
    assert p.read_bytes() == b"".join(parts)
    # partial writes resume mid-part
    calls = []
    real_writev = os.writev

    def short_writev(fd_, bufs):
        calls.append(len(bufs))
        n = real_writev(fd_, [memoryview(bufs[0])[:5]])
        return n

    monkeypatch.setattr(os, "writev", short_writev)
    p2 = tmp_path / "short.bin"
    fd = os.open(str(p2), os.O_WRONLY | os.O_CREAT)
    try:
        sink_mod._writev_all(fd, parts)
    finally:
        os.close(fd)
    assert p2.read_bytes() == b"".join(parts)
    assert len(calls) > len(parts)  # every 13-byte part took >1 call


@pytest.fixture
def fresh_autotune():
    from parquet_tpu.io.sink import write_autotune

    write_autotune().reset()
    yield write_autotune()
    write_autotune().reset()


def test_write_autotune_grows_then_decays(fresh_autotune, monkeypatch):
    from parquet_tpu.io.sink import DEFAULT_WRITE_BUFFER, write_buffer_bytes

    monkeypatch.delenv("PARQUET_TPU_WRITE_BUFFER", raising=False)
    monkeypatch.delenv("PARQUET_TPU_WRITE_AUTOTUNE", raising=False)
    hot = WriteStats(row_groups=6, sink_flushes=120, bytes_buffered=1)
    fresh_autotune.observe(hot)
    assert write_buffer_bytes() == DEFAULT_WRITE_BUFFER * 2
    fresh_autotune.observe(hot)
    assert write_buffer_bytes() == DEFAULT_WRITE_BUFFER * 4
    cold = WriteStats(row_groups=6, sink_flushes=6, bytes_buffered=1)
    fresh_autotune.observe(cold)
    assert write_buffer_bytes() == DEFAULT_WRITE_BUFFER * 2
    fresh_autotune.observe(cold)
    fresh_autotune.observe(cold)
    assert write_buffer_bytes() == DEFAULT_WRITE_BUFFER  # back to default
    # pass-through writers (nothing buffered) are no signal either way
    fresh_autotune.observe(WriteStats(row_groups=6, sink_flushes=0))
    assert write_buffer_bytes() == DEFAULT_WRITE_BUFFER


def test_write_buffer_garbage_env_is_unset_consistently(fresh_autotune,
                                                        monkeypatch):
    # an unparseable PARQUET_TPU_WRITE_BUFFER counts as unset in BOTH
    # resolution paths: the size falls back to tuner/default AND the sink
    # stays tunable (a half-pinned state would freeze a stale suggestion)
    from parquet_tpu.io.sink import (DEFAULT_WRITE_BUFFER, BufferedSink,
                                     write_buffer_bytes)

    monkeypatch.setenv("PARQUET_TPU_WRITE_BUFFER", "4mb")
    monkeypatch.delenv("PARQUET_TPU_WRITE_AUTOTUNE", raising=False)
    assert write_buffer_bytes() == DEFAULT_WRITE_BUFFER
    assert BufferedSink(_CountingSink())._tunable is True
    monkeypatch.setenv("PARQUET_TPU_WRITE_BUFFER", "1024")
    assert write_buffer_bytes() == 1024
    assert BufferedSink(_CountingSink())._tunable is False


def test_write_autotune_env_pin_wins(fresh_autotune, monkeypatch):
    from parquet_tpu.io.sink import write_buffer_bytes

    fresh_autotune.observe(WriteStats(row_groups=1, sink_flushes=100,
                                      bytes_buffered=1))
    assert fresh_autotune.suggest() is not None
    monkeypatch.setenv("PARQUET_TPU_WRITE_BUFFER", "12345")
    assert write_buffer_bytes() == 12345  # explicit pin beats the tuner
    monkeypatch.delenv("PARQUET_TPU_WRITE_BUFFER", raising=False)
    monkeypatch.setenv("PARQUET_TPU_WRITE_AUTOTUNE", "0")
    from parquet_tpu.io.sink import DEFAULT_WRITE_BUFFER

    assert write_buffer_bytes() == DEFAULT_WRITE_BUFFER  # opt-out ignores it


def test_writer_close_feeds_the_autotuner(fresh_autotune, monkeypatch,
                                          tmp_path):
    monkeypatch.delenv("PARQUET_TPU_WRITE_BUFFER", raising=False)
    monkeypatch.delenv("PARQUET_TPU_WRITE_AUTOTUNE", raising=False)
    # a wide table against a tiny (tuner-suggested) buffer: every chunk's
    # page write flushes on its own, so flushes-per-row-group is the column
    # count — well past the raise threshold; close() must observe and grow
    # the suggestion for the NEXT writer
    wide = pa.table({f"c{i:02d}": pa.array(np.arange(2000, dtype=np.int64))
                     for i in range(12)})
    fresh_autotune.buffer = 1024  # as if tuned down; the writer reads it
    dest = tmp_path / "tuned.parquet"
    write_table(wide, str(dest), WriterOptions(row_group_size=500))
    assert fresh_autotune.suggest() == 2048  # observe() grew it
    # an env-pinned writer must NOT observe (the pin is authoritative)
    fresh_autotune.reset()
    monkeypatch.setenv("PARQUET_TPU_WRITE_BUFFER", "1024")
    dest2 = tmp_path / "pinned.parquet"
    write_table(wide, str(dest2), WriterOptions(row_group_size=500))
    assert fresh_autotune.suggest() is None
    assert dest.read_bytes() == dest2.read_bytes()  # size never changes bytes


def test_write_buffer_env_knob(table, monkeypatch, tmp_path):
    # PARQUET_TPU_WRITE_BUFFER=0 disables coalescing for path sinks; the
    # bytes are identical either way
    opts = WriterOptions(row_group_size=RG)
    monkeypatch.setenv("PARQUET_TPU_WRITE_BUFFER", "0")
    p0 = tmp_path / "nobuf.parquet"
    w0 = write_table(table, str(p0), opts)
    assert w0.write_stats.sink_flushes == 0
    monkeypatch.setenv("PARQUET_TPU_WRITE_BUFFER", str(1 << 16))
    p1 = tmp_path / "buf.parquet"
    w1 = write_table(table, str(p1), opts)
    assert w1.write_stats.sink_flushes > 0
    assert p0.read_bytes() == p1.read_bytes()


# ---------------------------------------------------------------------------
# overlap x writer faults: no torn destination, ever
# ---------------------------------------------------------------------------
@pytest.fixture()
def force_overlap(monkeypatch):
    monkeypatch.setenv("PARQUET_TPU_WRITE_OVERLAP", "force")


def test_enospc_with_overlap_aborts_clean(tmp_path, table, schema,
                                          force_overlap):
    dest = tmp_path / "enospc.parquet"
    sink = FaultInjectingSink(AtomicFileSink(str(dest)), enospc_at_byte=8192)
    with pytest.raises(OSError) as ei:
        with ParquetWriter(sink, schema,
                           WriterOptions(row_group_size=RG)) as w:
            for start in range(0, table.num_rows, RG):
                part = table.slice(start, RG)
                w.write_row_group(columns_from_arrow(part, schema),
                                  part.num_rows)
    assert ei.value.errno == errno.ENOSPC
    assert w._inflight is None  # abort cancelled the queued encode
    sink.abort()
    assert not dest.exists()
    assert _no_temps(tmp_path)


def test_crash_with_overlap_leaves_dest_absent(tmp_path, table, schema,
                                               force_overlap):
    dest = tmp_path / "crash.parquet"
    sink = FaultInjectingSink(AtomicFileSink(str(dest)), crash_at_byte=3000)
    with pytest.raises(InjectedWriterCrash):
        with ParquetWriter(sink, schema,
                           WriterOptions(row_group_size=RG)) as w:
            for start in range(0, table.num_rows, RG):
                part = table.slice(start, RG)
                w.write_row_group(columns_from_arrow(part, schema),
                                  part.num_rows)
    assert w._inflight is None
    assert not dest.exists()
    sink.abort()
    assert _no_temps(tmp_path)


def test_short_write_with_overlap_and_buffer_surfaces(table, schema,
                                                      force_overlap):
    inj = FaultInjectingSink(io.BytesIO(), seed=3, short_write_rate=0.3)
    sink = BufferedSink(inj, buffer_bytes=1 << 16)
    with pytest.raises(OSError, match="short write"):
        with ParquetWriter(sink, schema,
                           WriterOptions(row_group_size=RG)) as w:
            for start in range(0, table.num_rows, RG):
                part = table.slice(start, RG)
                w.write_row_group(columns_from_arrow(part, schema),
                                  part.num_rows)
    assert inj.stats.injected_short_writes >= 1


def test_crash_matrix_with_overlap_and_buffered_sink(tmp_path, table,
                                                     force_overlap):
    dest = str(tmp_path / "matrix.parquet")
    opts = WriterOptions(row_group_size=RG)
    results = crash_consistency_check(
        lambda sink: write_table(table, sink, opts), dest,
        samples=8, seed=7, buffered=True)
    assert [r["outcome"] for r in results[:-1]] == ["absent"] * (
        len(results) - 1)
    assert results[-1] == {"offset": None, "outcome": "clean"}
    assert _no_temps(tmp_path)
    assert verify_file(dest).ok


def test_abort_mid_stream_cancels_inflight(tmp_path, table, schema,
                                           force_overlap):
    dest = tmp_path / "aborted.parquet"
    w = ParquetWriter(str(dest), schema, WriterOptions(row_group_size=RG))
    w.write_row_group(columns_from_arrow(table.slice(0, RG), schema), RG)
    assert w._inflight is not None  # the group is pended, not yet emitted
    w.abort()
    assert w._inflight is None
    assert not dest.exists()
    assert _no_temps(tmp_path)
    with pytest.raises(ValueError, match="aborted"):
        w.write_row_group(columns_from_arrow(table.slice(0, RG), schema), RG)


def test_flush_emits_the_pended_group(table, schema, force_overlap,
                                      monkeypatch):
    monkeypatch.setenv("PARQUET_TPU_WRITE_OVERLAP", "force")
    buf = io.BytesIO()
    w = ParquetWriter(buf, schema, WriterOptions(row_group_size=RG))
    w.write_row_group(columns_from_arrow(table.slice(0, RG), schema), RG)
    assert len(w._row_groups) == 0  # still in flight
    w.flush()
    assert len(w._row_groups) == 1 and w._inflight is None
    w.close()
    assert ParquetFile(buf.getvalue()).read().to_arrow().equals(
        table.slice(0, RG))
